"""Skeleton container + Precomputed skeleton codec + postprocessing.

Capability parity with cloud-volume's Skeleton type and kimimaro's
postprocess (reference consumers: /root/reference/igneous/tasks/skeleton.py
:810-916 merge via Skeleton.simple_merge + kimimaro.postprocess).

Precomputed skeleton fragment format (Neuroglancer spec):
  uint32le num_vertices, uint32le num_edges,
  float32le positions[3 * V] (x, y, z physical units),
  uint32le edges[2 * E],
  then each vertex attribute (info order): radius float32[V],
  vertex_types uint8[V].
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence

import numpy as np

DEFAULT_ATTRIBUTES = [
  {"id": "radius", "data_type": "float32", "num_components": 1},
  {"id": "vertex_types", "data_type": "uint8", "num_components": 1},
]

_DTYPES = {"float32": "<f4", "uint8": "u1", "uint16": "<u2", "uint32": "<u4",
           "int8": "i1", "int16": "<i2", "int32": "<i4", "float64": "<f8"}


class Skeleton:
  def __init__(
    self,
    vertices=None,
    edges=None,
    radii=None,
    vertex_types=None,
    extra_attributes: Optional[Dict[str, np.ndarray]] = None,
  ):
    self.vertices = (
      np.zeros((0, 3), np.float32)
      if vertices is None
      else np.asarray(vertices, np.float32).reshape(-1, 3)
    )
    n = len(self.vertices)
    self.edges = (
      np.zeros((0, 2), np.uint32)
      if edges is None
      else np.asarray(edges, np.uint32).reshape(-1, 2)
    )
    self.radii = (
      np.full(n, -1, np.float32) if radii is None
      else np.asarray(radii, np.float32)
    )
    self.vertex_types = (
      np.zeros(n, np.uint8) if vertex_types is None
      else np.asarray(vertex_types, np.uint8)
    )
    self.extra_attributes = dict(extra_attributes or {})

  def __len__(self):
    return len(self.vertices)

  @property
  def empty(self) -> bool:
    return len(self.vertices) == 0

  def clone(self) -> "Skeleton":
    return Skeleton(
      self.vertices.copy(), self.edges.copy(), self.radii.copy(),
      self.vertex_types.copy(),
      {k: v.copy() for k, v in self.extra_attributes.items()},
    )

  # -- merge / cleanup ------------------------------------------------------

  @classmethod
  def simple_merge(cls, skeletons: Sequence["Skeleton"]) -> "Skeleton":
    skeletons = [s for s in skeletons if not s.empty]
    if not skeletons:
      return cls()
    voff = 0
    verts, edges, radii, vtypes = [], [], [], []
    extras: Dict[str, List[np.ndarray]] = {}
    for s in skeletons:
      verts.append(s.vertices)
      edges.append(s.edges + np.uint32(voff))
      radii.append(s.radii)
      vtypes.append(s.vertex_types)
      for k, v in s.extra_attributes.items():
        extras.setdefault(k, []).append(v)
      voff += len(s.vertices)
    return cls(
      np.concatenate(verts), np.concatenate(edges),
      np.concatenate(radii), np.concatenate(vtypes),
      {k: np.concatenate(v) for k, v in extras.items()},
    )

  def consolidate(self) -> "Skeleton":
    """Weld identical vertex positions, dedupe edges, drop self-loops."""
    if self.empty:
      return self.clone()
    uniq, inverse = np.unique(self.vertices, axis=0, return_inverse=True)
    edges = inverse[self.edges.astype(np.int64)].astype(np.uint32)
    edges = np.sort(edges, axis=1)
    edges = edges[edges[:, 0] != edges[:, 1]]
    edges = np.unique(edges, axis=0) if len(edges) else edges
    # carry attributes from the first occurrence of each welded vertex
    first = np.full(len(uniq), len(self.vertices), dtype=np.int64)
    order = np.arange(len(self.vertices))
    np.minimum.at(first, inverse, order)
    out = Skeleton(
      uniq, edges, self.radii[first], self.vertex_types[first],
      {k: v[first] for k, v in self.extra_attributes.items()},
    )
    return out

  def components_by_vertex(self) -> np.ndarray:
    """Connected component id per vertex (union-find over edges)."""
    n = len(self.vertices)
    parent = np.arange(n, dtype=np.int64)

    def find(x):
      root = x
      while parent[root] != root:
        root = parent[root]
      while parent[x] != root:
        parent[x], x = root, parent[x]
      return root

    for a, b in self.edges.astype(np.int64):
      ra, rb = find(a), find(b)
      if ra != rb:
        parent[max(ra, rb)] = min(ra, rb)
    return np.array([find(i) for i in range(n)], dtype=np.int64)

  def cable_length(self) -> float:
    if len(self.edges) == 0:
      return 0.0
    d = self.vertices[self.edges[:, 0].astype(np.int64)] - \
        self.vertices[self.edges[:, 1].astype(np.int64)]
    return float(np.linalg.norm(d, axis=1).sum())

  def crop(self, bbox) -> "Skeleton":
    """Keep vertices inside bbox (physical units) and edges between them."""
    from .lib import Bbox  # noqa: F401  (type documented)

    keep = np.all(
      (self.vertices >= np.asarray(bbox.minpt, np.float32))
      & (self.vertices < np.asarray(bbox.maxpt, np.float32)),
      axis=1,
    )
    return self._select_vertices(keep)

  def _select_vertices(self, keep: np.ndarray) -> "Skeleton":
    remap = np.cumsum(keep) - 1
    edges = self.edges.astype(np.int64)
    emask = keep[edges[:, 0]] & keep[edges[:, 1]]
    return Skeleton(
      self.vertices[keep],
      remap[edges[emask]].astype(np.uint32),
      self.radii[keep],
      self.vertex_types[keep],
      {k: v[keep] for k, v in self.extra_attributes.items()},
    )

  # -- codec ----------------------------------------------------------------

  def to_precomputed(self) -> bytes:
    out = [
      struct.pack("<II", len(self.vertices), len(self.edges)),
      self.vertices.astype("<f4").tobytes(),
      self.edges.astype("<u4").tobytes(),
      self.radii.astype("<f4").tobytes(),
      self.vertex_types.astype("u1").tobytes(),
    ]
    for name in sorted(self.extra_attributes):
      arr = np.ascontiguousarray(self.extra_attributes[name])
      # pin the wire dtype to what the info declares (extras are float32
      # single-component by convention here): an accidental float64 array
      # would silently shift every byte after it
      if arr.dtype.kind == "f" and arr.dtype.itemsize != 4:
        arr = arr.astype("<f4")
      out.append(arr.tobytes())
    return b"".join(out)

  @classmethod
  def from_precomputed(
    cls, data: bytes, vertex_attributes: Optional[List[dict]] = None
  ) -> "Skeleton":
    attrs = vertex_attributes or DEFAULT_ATTRIBUTES
    nv, ne = struct.unpack_from("<II", data, 0)
    pos = 8
    vertices = np.frombuffer(data, "<f4", 3 * nv, pos).reshape(-1, 3)
    pos += 12 * nv
    edges = np.frombuffer(data, "<u4", 2 * ne, pos).reshape(-1, 2)
    pos += 8 * ne
    radii = None
    vertex_types = None
    extra = {}
    for att in attrs:
      dt = np.dtype(_DTYPES[att["data_type"]])
      count = nv * int(att.get("num_components", 1))
      arr = np.frombuffer(data, dt, count, pos)
      pos += dt.itemsize * count
      if att["id"] == "radius":
        radii = arr.astype(np.float32)
      elif att["id"] == "vertex_types":
        vertex_types = arr.astype(np.uint8)
      else:
        extra[att["id"]] = arr.copy()
    return cls(vertices.copy(), edges.copy(), radii, vertex_types, extra)


def to_swc(skel: Skeleton, label: Optional[int] = None) -> str:
  """SWC text export (`igneous skeleton convert` capability).

  SWC rows: id type x y z radius parent; forests emit one root (-1
  parent) per connected component."""
  lines = []
  if label is not None:
    lines.append(f"# label {label}")
  n = len(skel.vertices)
  adj: Dict[int, List[int]] = {}
  for a, b in skel.edges.astype(np.int64):
    adj.setdefault(int(a), []).append(int(b))
    adj.setdefault(int(b), []).append(int(a))

  parent = np.full(n, -2, dtype=np.int64)  # -2 = unvisited
  order: List[int] = []
  for start in range(n):
    if parent[start] != -2:
      continue
    parent[start] = -1
    stack = [start]
    while stack:
      cur = stack.pop()
      order.append(cur)
      for nxt in adj.get(cur, []):
        if parent[nxt] == -2:
          parent[nxt] = cur
          stack.append(nxt)

  swc_id = np.zeros(n, dtype=np.int64)
  for i, v in enumerate(order, start=1):
    swc_id[v] = i
  for v in order:
    x, y, z = skel.vertices[v]
    r = float(skel.radii[v]) if skel.radii[v] > 0 else 1.0
    p = -1 if parent[v] < 0 else int(swc_id[parent[v]])
    t = int(skel.vertex_types[v])
    lines.append(
      f"{int(swc_id[v])} {t} {x:.1f} {y:.1f} {z:.1f} {r:.3f} {p}"
    )
  return "\n".join(lines) + "\n"


def from_swc(text: str) -> Skeleton:
  verts, radii, types = [], [], []
  edges = []
  id_map: Dict[int, int] = {}
  rows = []
  for line in text.splitlines():
    line = line.strip()
    if not line or line.startswith("#"):
      continue
    parts = line.split()
    rows.append((
      int(parts[0]), int(parts[1]),
      float(parts[2]), float(parts[3]), float(parts[4]),
      float(parts[5]), int(parts[6]),
    ))
  for sid, t, x, y, z, r, _p in rows:
    id_map[sid] = len(verts)
    verts.append((x, y, z))
    radii.append(r)
    types.append(t)
  for sid, _t, _x, _y, _z, _r, p in rows:
    if p >= 0:
      edges.append((id_map[p], id_map[sid]))
  return Skeleton(verts, edges, radii=radii, vertex_types=types)


def postprocess(
  skel: Skeleton,
  dust_threshold: float = 1000.0,
  tick_threshold: float = 900.0,
) -> Skeleton:
  """kimimaro.postprocess parity: weld, drop dust components by cable
  length (physical units), prune short terminal twigs ("ticks")."""
  skel = skel.consolidate()
  if skel.empty:
    return skel

  # dust: remove connected components with cable length < dust_threshold
  comp = skel.components_by_vertex()
  edges = skel.edges.astype(np.int64)
  seg_len = np.linalg.norm(
    skel.vertices[edges[:, 0]] - skel.vertices[edges[:, 1]], axis=1
  )
  comp_len: Dict[int, float] = {}
  for c, l in zip(comp[edges[:, 0]], seg_len):
    comp_len[c] = comp_len.get(c, 0.0) + float(l)
  keep_comp = {c for c, l in comp_len.items() if l >= dust_threshold}
  keep = np.array([c in keep_comp for c in comp], dtype=bool)
  skel = skel._select_vertices(keep)
  if skel.empty:
    return skel

  # ticks: repeatedly prune terminal branches shorter than tick_threshold
  # (never removing the entire component)
  changed = True
  while changed:
    changed = False
    edges = skel.edges.astype(np.int64)
    n = len(skel.vertices)
    deg = np.bincount(edges.reshape(-1), minlength=n)
    adj: Dict[int, List[int]] = {}
    for idx, (a, b) in enumerate(edges):
      adj.setdefault(int(a), []).append(idx)
      adj.setdefault(int(b), []).append(idx)
    seg_len = np.linalg.norm(
      skel.vertices[edges[:, 0]] - skel.vertices[edges[:, 1]], axis=1
    )
    remove_vertices = set()
    for leaf in np.flatnonzero(deg == 1):
      # walk from the leaf toward the next branch point (deg >= 3)
      path = [int(leaf)]
      length = 0.0
      prev = -1
      cur = int(leaf)
      ended_at_branch = False
      while length < tick_threshold:
        nxt = None
        for eidx in adj.get(cur, []):
          a, b = int(edges[eidx, 0]), int(edges[eidx, 1])
          other = b if a == cur else a
          if other != prev:
            nxt = (other, eidx)
            break
        if nxt is None:
          break  # dead end: the twig is the whole path (bare component)
        other, eidx = nxt
        length += float(seg_len[eidx])
        if deg[other] >= 3:
          ended_at_branch = True
          break
        path.append(other)
        prev, cur = cur, other
      # only prune twigs hanging off a branch point; a bare path with no
      # branch point is the component itself and stays
      if ended_at_branch and length < tick_threshold:
        remove_vertices.update(path)
    if remove_vertices:
      keep = np.ones(len(skel.vertices), dtype=bool)
      keep[list(remove_vertices)] = False
      pruned = skel._select_vertices(keep)
      if not pruned.empty:
        skel = pruned
        changed = True
  return skel
