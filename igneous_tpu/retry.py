"""Unified retry policy for every network seam (ISSUE 1 tentpole §4).

storage_http.py, storage_gcs.py, storage_s3.py, and graphene_http.py all
talk to eventually-available services and previously each hard-coded its
own backoff constants. This module is the single source of truth:
``RetryPolicy`` carries base delay, cap, jitter mode, and an attempt
budget; callers ask it "should attempt N retry, and after how long?" and
report outcomes through telemetry counters so operators can see retry
pressure (``igneous_tpu.telemetry.counters_snapshot()``).

Env overrides (read at policy construction so workers can be tuned
without code changes):

  IGNEOUS_RETRY_ATTEMPTS   total attempts incl. the first (default 6)
  IGNEOUS_RETRY_BASE_S     first backoff delay (default 0.25)
  IGNEOUS_RETRY_CAP_S      max single delay (default 30)
  IGNEOUS_RETRY_BUDGET_S   total sleep budget per operation (default 120)

The ``sleep_fn``/``rng`` seams exist so the chaos harness and unit tests
run retry schedules deterministically without wall-clock sleeps.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Callable, Optional

from .analysis import knobs

RETRYABLE_STATUS = (408, 429, 500, 502, 503, 504)


@dataclass
class RetryPolicy:
  """Exponential backoff with full jitter and a total-sleep budget.

  attempts: total tries including the first (1 = no retries).
  base_s/cap_s: delay = min(cap, base * 2**retry_index), jittered.
  budget_s: once cumulative planned sleep exceeds this, stop retrying
    even if attempts remain (bounds worst-case task latency under a 503
    storm — the queue's at-least-once delivery is the outer retry loop).
  jitter: "full" (uniform [0, delay], the AWS-recommended default) or
    "none" (deterministic, used by tests and the chaos soak).
  """

  attempts: int = 6
  base_s: float = 0.25
  cap_s: float = 30.0
  budget_s: float = 120.0
  jitter: str = "full"
  sleep_fn: Callable[[float], None] = field(default=None, repr=False)
  rng: random.Random = field(default=None, repr=False)

  def __post_init__(self):
    if self.sleep_fn is None:
      import time

      self.sleep_fn = time.sleep
    if self.rng is None:
      self.rng = random

  @classmethod
  def from_env(cls, **overrides) -> "RetryPolicy":
    kw = dict(
      attempts=knobs.get_int("IGNEOUS_RETRY_ATTEMPTS"),
      base_s=knobs.get_float("IGNEOUS_RETRY_BASE_S"),
      cap_s=knobs.get_float("IGNEOUS_RETRY_CAP_S"),
      budget_s=knobs.get_float("IGNEOUS_RETRY_BUDGET_S"),
    )
    kw.update(overrides)
    return cls(**kw)

  def delay(self, retry_index: int) -> float:
    """Planned delay before retry number ``retry_index`` (0-based)."""
    d = min(self.cap_s, self.base_s * (2.0 ** retry_index))
    if self.jitter == "full":
      d = self.rng.random() * d
    return d

  def retries(self, counter: Optional[str] = None):
    """Yield retry indices, sleeping between them, until attempts or the
    sleep budget is exhausted. The FIRST attempt is the caller's — this
    iterator yields once per RETRY and sleeps before yielding.

      for _ in policy.retries("storage_http"):
        # re-issue the request
    """
    from . import telemetry

    slept = 0.0
    for i in range(max(self.attempts - 1, 0)):
      d = self.delay(i)
      if slept + d > self.budget_s:
        return
      self.sleep_fn(d)
      slept += d
      if counter:
        telemetry.incr(f"retries.{counter}")
      yield i


_DEFAULT: Optional[RetryPolicy] = None


def default_policy() -> RetryPolicy:
  """Process-wide policy (env-configured, constructed once)."""
  global _DEFAULT
  if _DEFAULT is None:
    _DEFAULT = RetryPolicy.from_env()
  return _DEFAULT


def set_default_policy(policy: Optional[RetryPolicy]):
  """Override the process-wide policy (None resets to env config).
  Used by tests and the chaos soak to run deterministic schedules."""
  global _DEFAULT
  _DEFAULT = policy
