"""Shared chunk decode cache: compressed-domain residency for hot reads.

The e2e profile (BENCH_r05) pins the codec wall: single-thread inflate at
~350-600 MB/s plus the chunk decode bounds every read path, while the same
chunks are fetched repeatedly — the pipeline prefetch pool, the lease
batcher's cutout prefetch, and overlapping task cutouts all re-download
and re-decode bytes a sibling just produced. This module keeps DECODED
chunks in one process-wide LRU so a repeated read costs a digest instead
of an inflate + codec pass (Palace, arXiv:2509.26213, makes the same
residency argument for accelerator pipelines).

Keying — correctness without coordination: entries are keyed by
``(layer path, mip, chunk bbox, digest of the STORED bytes)``. The digest
is computed over the wire bytes each time they are fetched, so a chunk
overwritten by a concurrent writer simply never matches a stale entry —
a hit is always byte-equivalent to decoding what storage currently holds.
Explicit ``invalidate(path, mip)`` (wired into Volume.upload/delete, the
pipeline runner's write joins, and the lease batcher's round fencing —
the same (path, mip) write-fencing discipline PR 3's review established)
is memory hygiene: it frees doomed entries early, it is not what keeps
reads correct.

Budget: a byte budget carved from the staged pipeline's buffer solver
(``IGNEOUS_PIPELINE_MEM_MB``-derived) so the cache and the stage buffers
are reasoned about together:

  IGNEOUS_CHUNK_CACHE      on|off|auto   master switch (auto = on)
  IGNEOUS_CHUNK_CACHE_MB   int           byte budget override
                                         (default: pipeline budget / 8)

Entries are stored read-only (``writeable=False``); consumers copy voxels
into their own cutout assembly, never mutate the cached array.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Iterable, Optional, Tuple

import numpy as np

from . import telemetry
from .analysis import knobs, racecheck


def enabled() -> bool:
  val = knobs.get_str("IGNEOUS_CHUNK_CACHE").strip().lower()
  if val in ("0", "off", "false", "no"):
    return False
  return True


def budget_bytes() -> int:
  mb = knobs.get_float("IGNEOUS_CHUNK_CACHE_MB")
  if mb:
    return max(int(mb * 1e6), 1)
  from .pipeline import config

  return max(config.memory_budget_bytes() // 8, 1)


def digest(data: bytes) -> bytes:
  """Digest of the STORED (wire) bytes — the part of the key that makes
  concurrent writers safe without coordination."""
  return hashlib.blake2b(data, digest_size=16).digest()


class ChunkDecodeCache:
  """Byte-budgeted LRU of decoded chunks, keyed on stored-bytes digests."""

  def __init__(self, budget: Optional[int] = None):
    self._budget = budget
    self._lock = threading.Lock()
    self._entries = racecheck.guard(  # guarded-by: self._lock
      OrderedDict(), self._lock, "ChunkDecodeCache._entries")
    self._by_layer = racecheck.guard(  # guarded-by: self._lock
      {}, self._lock, "ChunkDecodeCache._by_layer")
    self._bytes = 0  # guarded-by: self._lock

  @property
  def budget(self) -> int:
    return self._budget if self._budget is not None else budget_bytes()

  def make_key(self, path: str, mip: int, bbox_key, stored: bytes) -> tuple:
    # rstrip matches PrecomputedMetadata's cloudpath normalization, so
    # task-parameter paths and Volume-normalized paths address the same
    # entries (both key and invalidation sides use this)
    return (path.rstrip("/"), int(mip), bbox_key, digest(stored))

  def get(self, key: tuple) -> Optional[np.ndarray]:
    with self._lock:
      arr = self._entries.get(key)
      if arr is None:
        telemetry.incr("chunk_cache.misses")
        return None
      self._entries.move_to_end(key)
    telemetry.incr("chunk_cache.hits")
    telemetry.incr("chunk_cache.bytes_saved", int(arr.nbytes))
    return arr

  def put(self, key: tuple, arr: np.ndarray) -> np.ndarray:
    """Insert; returns the READ-ONLY view actually cached (callers hand
    that view out so no writable alias of a cached entry escapes)."""
    nbytes = int(arr.nbytes)
    arr = arr.view()
    arr.flags.writeable = False
    if nbytes > self.budget:
      return arr  # one oversized chunk must not wipe the working set
    with self._lock:
      old = self._entries.pop(key, None)
      if old is not None:
        self._bytes -= int(old.nbytes)
      self._entries[key] = arr
      self._by_layer.setdefault((key[0], key[1]), set()).add(key)
      self._bytes += nbytes
      while self._bytes > self.budget and self._entries:
        self._evict_oldest_locked()
      telemetry.gauge_max("chunk_cache.bytes", self._bytes)
    return arr

  def _evict_oldest_locked(self) -> None:
    old_key, old_arr = self._entries.popitem(last=False)
    self._bytes -= int(old_arr.nbytes)
    layer = self._by_layer.get((old_key[0], old_key[1]))
    if layer is not None:
      layer.discard(old_key)
      if not layer:
        self._by_layer.pop((old_key[0], old_key[1]), None)
    telemetry.incr("chunk_cache.evicted")

  def invalidate(self, path: str, mip: Optional[int] = None) -> int:
    """Drop every entry of (path, mip) — or of all mips when ``mip`` is
    None. Returns the number of entries dropped."""
    path = path.rstrip("/")
    with self._lock:
      if mip is None:
        layers = [k for k in self._by_layer if k[0] == path]
      else:
        layers = [(path, int(mip))]
      dropped = 0
      for layer in layers:
        for key in self._by_layer.pop(layer, ()):
          arr = self._entries.pop(key, None)
          if arr is not None:
            self._bytes -= int(arr.nbytes)
            dropped += 1
    if dropped:
      telemetry.incr("chunk_cache.invalidated", dropped)
    return dropped

  def clear(self) -> None:
    with self._lock:
      self._entries.clear()
      self._by_layer.clear()
      self._bytes = 0

  @property
  def nbytes(self) -> int:
    with self._lock:
      return self._bytes

  def __len__(self) -> int:
    with self._lock:
      return len(self._entries)


_SHARED: Optional[ChunkDecodeCache] = None
_SHARED_LOCK = threading.Lock()

# Invalidation fan-out (ISSUE 9 satellite): the decode cache is no longer
# the only consumer of "this (path, mip) was just rewritten" — the serve
# tier's stored-bytes tiers (RAM/SSD) key entries by layer+chunk and must
# drop them on overwrite/delete. Rather than having serve reach into
# Volume internals, `invalidate()` below is THE shared entry point:
# Volume.upload/delete, the pipeline runner's write joins, and serve's
# own write-back all call it, and every registered hook hears about it.
_INVALIDATION_HOOKS: list = []
_HOOKS_LOCK = threading.Lock()


def register_invalidation_hook(fn) -> None:
  """Register ``fn(path, mip_or_None)`` to be called on every
  ``invalidate()``/``invalidate_writes()``. Hooks must be fast and must
  not raise (failures are counted, never propagated)."""
  with _HOOKS_LOCK:
    if fn not in _INVALIDATION_HOOKS:
      _INVALIDATION_HOOKS.append(fn)


def unregister_invalidation_hook(fn) -> None:
  with _HOOKS_LOCK:
    try:
      _INVALIDATION_HOOKS.remove(fn)
    except ValueError:
      pass


def _notify_hooks(path: str, mip: Optional[int]) -> None:
  with _HOOKS_LOCK:
    hooks = list(_INVALIDATION_HOOKS)
  for fn in hooks:
    try:
      fn(path, mip)
    except Exception:
      telemetry.incr("chunk_cache.hook_failed")


def shared_cache() -> ChunkDecodeCache:
  global _SHARED
  with _SHARED_LOCK:
    if _SHARED is None:
      _SHARED = ChunkDecodeCache()
    return _SHARED


def lookup(path: str, mip: int, bbox_key, stored: bytes):
  """(key, decoded chunk or None). The key is returned either way so a
  miss can ``store`` its decode under the digest already computed."""
  cache = shared_cache()
  key = cache.make_key(path, mip, bbox_key, stored)
  return key, cache.get(key)


def store(key: tuple, arr: np.ndarray) -> np.ndarray:
  return shared_cache().put(key, arr)


def invalidate(path: str, mip: Optional[int] = None) -> int:
  # hooks fire even when the decode cache was never instantiated: a
  # serve tier may be the only cache alive in this process
  _notify_hooks(path, mip)
  if _SHARED is None:
    return 0
  return _SHARED.invalidate(path, mip)


def invalidate_writes(writes: Iterable[Tuple[str, int]]) -> None:
  """Invalidate a StagePlan-style set of (layer path, mip) writes."""
  for path, mip in writes:
    _notify_hooks(path, mip)
    if _SHARED is not None:
      _SHARED.invalidate(path, mip)


def clear() -> None:
  if _SHARED is not None:
    _SHARED.clear()
