"""Neuroglancer ``compressed_segmentation`` codec.

Format (github.com/google/neuroglancer, sliceview/compressed_segmentation):
the chunk is split per channel into a grid of blocks (default 8x8x8). The
file is a sequence of little-endian uint32 words:

  [channel offset table: num_channels words, offset of each channel start]
  per channel:
    [block headers: 2 words per block, x-fastest block order]
       word0 = lookup_table_offset (low 24 bits) | (encoded_bits << 24)
       word1 = encoded_values_offset
       (offsets in uint32 units relative to the channel start)
    [lookup tables + packed encoded values, interleaved as emitted]

Within a block, voxels are enumerated x-fastest over the block extent
*clipped to the chunk bounds*; each voxel stores an ``encoded_bits``-wide
index into the block's lookup table, packed LSB-first into uint32 words.
``encoded_bits`` ∈ {0,1,2,4,8,16,32}. Lookup table entries are uint32 (one
word) or uint64 (two words, low word first) matching the chunk dtype.

Blocks with identical lookup tables may share them; this encoder reuses the
previous block's table when equal (a common win on uniform regions).

Implementations, fastest first:

  1. native C++ (igneous_tpu/native/csrc/cseg.cpp), when a toolchain exists;
  2. bulk-NumPy (``_encode_channel`` / ``_decompress_np``): every block of
     the chunk is encoded/decoded at once — blocks are gathered into a
     (voxels, blocks) matrix per clipped-shape category, per-block tables
     come from one axis-wise sort, and bit packing/unpacking runs as one
     shift/or reduction across all blocks sharing a bit width;
  3. the original per-block Python loops (``_encode_channel_loop`` /
     ``_decompress_loop``), kept as the executable specification: the
     golden-fixture tests pin that (1) and (2) produce byte-identical
     streams to (3).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

VALID_BITS = (0, 1, 2, 4, 8, 16, 32)

# _pick_bits as a table: index of the first VALID_BITS entry whose capacity
# (2^bits distinct values) covers ndist
_BITS_CAPACITY = np.array([1, 2, 4, 16, 256, 65536, 2**32], dtype=np.int64)
_BITS_VALUES = np.array(VALID_BITS, dtype=np.uint32)


def _pick_bits(n_distinct: int) -> int:
  need = max(int(np.ceil(np.log2(max(n_distinct, 1)))), 0)
  for b in VALID_BITS:
    if b >= need:
      return b
  raise ValueError(f"Too many distinct values in block: {n_distinct}")


def _ragged_arange(lengths: np.ndarray) -> np.ndarray:
  """[0..l0), [0..l1), ... concatenated (the ragged scatter index helper)."""
  lengths = np.asarray(lengths, dtype=np.int64)
  if lengths.size == 0 or int(lengths.sum()) == 0:
    return np.zeros(0, dtype=np.int64)
  excl = np.cumsum(lengths) - lengths
  return np.arange(int(lengths.sum()), dtype=np.int64) - np.repeat(excl, lengths)


def _axis_splits(extent: int, block: int):
  """[(start, stop, clipped_block_extent)] partitioning one axis into the
  full-block run and the (optional) clipped remainder."""
  full = (extent // block) * block
  out = []
  if full:
    out.append((0, full, block))
  if extent - full:
    out.append((full, extent, extent - full))
  return out


def _block_categories(shape3, block_size):
  """The ≤8 corner regions of the chunk whose blocks share one clipped
  shape; each yields (x-split, y-split, z-split)."""
  sx, sy, sz = shape3
  bx, by, bz = block_size
  return [
    (xs, ys, zs)
    for zs in _axis_splits(sz, bz)
    for ys in _axis_splits(sy, by)
    for xs in _axis_splits(sx, bx)
  ]


def _category_geometry(cat):
  (x0, x1, cx), (y0, y1, cy), (z0, z1, cz) = cat
  return (x0, y0, z0), (cx, cy, cz), (
    (x1 - x0) // cx, (y1 - y0) // cy, (z1 - z0) // cz
  )


def _category_bids(cat, block_size, gx, gy):
  """GLOBAL block index of each of a category's blocks, x-fastest (the
  header/stream order of the format)."""
  (x0, y0, z0), _, (nbx, nby, nbz) = _category_geometry(cat)
  bx, by, bz = block_size
  bid = (
    (x0 // bx + np.arange(nbx))[:, None, None]
    + gx * (
      (y0 // by + np.arange(nby))[None, :, None]
      + gy * (z0 // bz + np.arange(nbz))[None, None, :]
    )
  )
  return bid.ravel(order="F").astype(np.int64)


def _category_6d(region, cblock, nblocks3):
  """The region as a 6-axis [vx, jx, vy, jy, vz, jz] logical view."""
  cx, cy, cz = cblock
  nbx, nby, nbz = nblocks3
  return region.reshape((cx, nbx, cy, nby, cz, nbz), order="F")


def _category_vox(region, cblock, nblocks3):
  """Gather one category's blocks into vox[(block), (voxel)] — one
  C-contiguous row per block with x-fastest voxel order (the loop's
  enumeration order), rows in x-fastest block order."""
  cx, cy, cz = cblock
  nbx, nby, nbz = nblocks3
  # transposing the 6-axis view to (jz,jy,jx,vz,vy,vx) and C-reshaping
  # merges to rows b = jx + nbx*(jy + nby*jz) and columns
  # v = vx + cx*(vy + cy*vz), both x-fastest
  return np.ascontiguousarray(
    _category_6d(region, cblock, nblocks3).transpose(5, 3, 1, 4, 2, 0)
  ).reshape(nbx * nby * nbz, cx * cy * cz)


def _encode_channel(chan: np.ndarray, block_size: Tuple[int, int, int]) -> np.ndarray:
  """Bulk-NumPy encode of one channel; byte-identical to
  ``_encode_channel_loop``. chan: (sx, sy, sz) uint32/uint64 → uint32 words.
  """
  sx, sy, sz = chan.shape
  bx, by, bz = block_size
  gx, gy, gz = -(-sx // bx), -(-sy // by), -(-sz // bz)
  nblocks = gx * gy * gz
  if nblocks == 0:
    return np.zeros(0, dtype=np.uint32)
  words_per_entry = 2 if chan.dtype.itemsize == 8 else 1

  ndist_g = np.zeros(nblocks, dtype=np.int64)
  bits_g = np.zeros(nblocks, dtype=np.uint32)
  vw_g = np.zeros(nblocks, dtype=np.int64)  # value words per block
  # per-category deferred pieces: (global block ids, sorted-unique stream)
  # for the table scatter and (block ids, packed matrix) for values
  table_parts = []  # (bids, uniques concatenated in per-category block order)
  value_parts = []  # (bids_subset, packed (nwords, nsel) uint32)

  for cat in _block_categories((sx, sy, sz), (bx, by, bz)):
    (x0, y0, z0), cblock, nblocks3 = _category_geometry(cat)
    cx, cy, cz = cblock
    nb = int(np.prod(nblocks3))
    nvox = cx * cy * cz
    bids = _category_bids(cat, (bx, by, bz), gx, gy)
    region = chan[x0 : x0 + cx * nblocks3[0],
                  y0 : y0 + cy * nblocks3[1],
                  z0 : z0 + cz * nblocks3[2]]
    six = _category_6d(region, cblock, nblocks3)
    # constant-block fast path: real segmentation chunks are dominated by
    # blocks interior to one object, and all-voxels-equal-the-first
    # decides membership with one compare pass instead of a sort
    firsts = np.ascontiguousarray(six[0, :, 0, :, 0, :])
    uni = (
      (six == firsts[None, :, None, :, None, :])
      .all(axis=(0, 2, 4))
      .ravel(order="F")
    )
    firsts = firsts.ravel(order="F")
    ndist = np.ones(nb, dtype=np.int64)
    bits = np.zeros(nb, dtype=np.uint32)

    if bool(uni.all()):
      ndist_g[bids] = 1
      table_parts.append((bids, ndist, firsts))
      continue

    vox = _category_vox(region, cblock, nblocks3)
    nu = np.nonzero(~uni)[0]
    voxn = vox[nu]
    order = np.argsort(voxn, axis=1)
    svox = np.take_along_axis(voxn, order, axis=1)
    newv = np.empty(svox.shape, dtype=bool)
    newv[:, 0] = True
    newv[:, 1:] = svox[:, 1:] != svox[:, :-1]
    ranks = np.cumsum(newv, axis=1, dtype=np.int32) - 1
    ndist_nu = (ranks[:, -1] + 1).astype(np.int64)
    inv = np.empty(svox.shape, dtype=np.uint32)
    np.put_along_axis(inv, order, ranks.view(np.uint32), axis=1)

    cap_idx = np.searchsorted(_BITS_CAPACITY, ndist_nu, side="left")
    if int(cap_idx.max(initial=0)) >= len(_BITS_VALUES):
      raise ValueError(
        f"Too many distinct values in block: {int(ndist_nu.max())}"
      )
    ndist[nu] = ndist_nu
    bits[nu] = _BITS_VALUES[cap_idx]
    ndist_g[bids] = ndist
    bits_g[bids] = bits

    # per-block tables, block order: uniform rows contribute their single
    # value, sorted rows their svox[b, newv[b]] run (the row-major boolean
    # flatten keeps the stream per-block-contiguous and ascending)
    starts_c = np.cumsum(ndist) - ndist
    stream = np.empty(int(ndist.sum()), dtype=chan.dtype)
    stream[starts_c[uni]] = firsts[uni]
    dst = np.repeat(starts_c[nu], ndist_nu) + _ragged_arange(ndist_nu)
    stream[dst] = svox[newv]
    table_parts.append((bids, ndist, stream))

    bits_nu = bits[nu]
    for b in np.unique(bits_nu):
      b = int(b)
      sel = np.nonzero(bits_nu == b)[0]
      vpw = 32 // b
      nwords = -(-nvox // vpw)
      padded = np.zeros((len(sel), nwords * vpw), dtype=np.uint32)
      padded[:, :nvox] = inv[sel]
      shifts = (np.arange(vpw, dtype=np.uint32) * np.uint32(b))
      packed = np.bitwise_or.reduce(
        padded.reshape(len(sel), nwords, vpw) << shifts[None, None, :], axis=2
      ).astype(np.uint32)
      gsel = bids[nu[sel]]
      vw_g[gsel] = nwords
      value_parts.append((gsel, packed))

  # tables of every block concatenated in GLOBAL block order (the order the
  # loop emits them), so consecutive-block table equality — the sharing
  # rule — is one ragged compare
  starts_t = np.cumsum(ndist_g) - ndist_g
  tabcat = np.zeros(int(ndist_g.sum()), dtype=chan.dtype)
  for bids, ndist, stream in table_parts:
    dst = np.repeat(starts_t[bids], ndist) + _ragged_arange(ndist)
    tabcat[dst] = stream

  shared = np.zeros(nblocks, dtype=bool)
  cand = np.nonzero(ndist_g[1:] == ndist_g[:-1])[0] + 1
  if len(cand):
    L = ndist_g[cand]
    off = _ragged_arange(L)
    neq = (
      tabcat[np.repeat(starts_t[cand], L) + off]
      != tabcat[np.repeat(starts_t[cand - 1], L) + off]
    )
    mismatches = np.add.reduceat(neq, np.cumsum(L) - L)
    shared[cand] = mismatches == 0
  # sharing compares content with the immediately previous block: a shared
  # run's members all equal the last EMITTED table, so pairwise equality is
  # transitive — the same decision the loop's prev_table makes

  tw = np.where(shared, 0, ndist_g * words_per_entry)
  block_words = tw + vw_g
  starts = 2 * nblocks + np.cumsum(block_words) - block_words
  last_emitted = np.maximum.accumulate(
    np.where(shared, 0, np.arange(nblocks))
  )
  table_offset = starts[last_emitted]
  if bool((table_offset >= (1 << 24)).any()):
    raise ValueError("lookup table offset exceeds 24 bits; use smaller chunks")
  values_offset = starts + tw

  total = int(2 * nblocks + block_words.sum())
  out = np.empty(total, dtype=np.uint32)
  headers = out[: 2 * nblocks].reshape(nblocks, 2)
  headers[:, 0] = table_offset.astype(np.uint32) | (bits_g << np.uint32(24))
  headers[:, 1] = values_offset.astype(np.uint32)

  em = ~shared
  if bool(em.any()):
    tab_em = tabcat[np.repeat(em, ndist_g)]
    L = (ndist_g * words_per_entry)[em]
    dst = np.repeat(starts[em], L) + _ragged_arange(L)
    if words_per_entry == 2:
      t64 = tab_em.astype(np.uint64)
      tab_words = np.empty(tab_em.size * 2, dtype=np.uint32)
      tab_words[0::2] = (t64 & np.uint64(0xFFFFFFFF)).astype(np.uint32)
      tab_words[1::2] = (t64 >> np.uint64(32)).astype(np.uint32)
    else:
      tab_words = tab_em.astype(np.uint32)
    out[dst] = tab_words

  for bids, packed in value_parts:
    dst = values_offset[bids][:, None] + np.arange(packed.shape[1])[None, :]
    out[dst] = packed
  return out


def _encode_channel_loop(chan: np.ndarray, block_size: Tuple[int, int, int]) -> np.ndarray:
  """Per-block reference encoder (the executable spec the vectorized and
  native paths are pinned byte-identical against).
  chan: (sx, sy, sz) array of uint32 or uint64. Returns uint32 words."""
  sx, sy, sz = chan.shape
  bx, by, bz = block_size
  gx, gy, gz = -(-sx // bx), -(-sy // by), -(-sz // bz)
  nblocks = gx * gy * gz

  words_per_entry = 2 if chan.dtype.itemsize == 8 else 1

  headers = np.zeros(nblocks * 2, dtype=np.uint32)
  body: list = []  # list of uint32 arrays appended after the headers
  body_len = 0
  prev_table = None
  prev_table_offset = 0

  bi = 0
  for z0 in range(0, gz * bz, bz):
    for y0 in range(0, gy * by, by):
      for x0 in range(0, gx * bx, bx):
        block = chan[x0 : min(x0 + bx, sx), y0 : min(y0 + by, sy), z0 : min(z0 + bz, sz)]
        # x-fastest flattening == Fortran order for an (x,y,z) array
        flat = block.reshape(-1, order="F")
        table, idx = np.unique(flat, return_inverse=True)
        bits = _pick_bits(len(table))

        if (
          prev_table is not None
          and len(prev_table) == len(table)
          and np.array_equal(prev_table, table)
        ):
          table_offset = prev_table_offset
        else:
          table_offset = 2 * nblocks + body_len
          if words_per_entry == 2:
            t64 = table.astype(np.uint64)
            tw = np.empty(len(t64) * 2, dtype=np.uint32)
            tw[0::2] = (t64 & np.uint64(0xFFFFFFFF)).astype(np.uint32)
            tw[1::2] = (t64 >> np.uint64(32)).astype(np.uint32)
          else:
            tw = table.astype(np.uint32)
          body.append(tw)
          body_len += len(tw)
          prev_table = table
          prev_table_offset = table_offset

        if table_offset >= (1 << 24):
          raise ValueError("lookup table offset exceeds 24 bits; use smaller chunks")

        values_offset = 2 * nblocks + body_len
        if bits > 0:
          n = len(idx)
          vals_per_word = 32 // bits
          nwords = -(-n // vals_per_word)
          padded = np.zeros(nwords * vals_per_word, dtype=np.uint32)
          padded[:n] = idx.astype(np.uint32)
          padded = padded.reshape(nwords, vals_per_word)
          shifts = (np.arange(vals_per_word, dtype=np.uint32) * np.uint32(bits))
          packed = np.bitwise_or.reduce(padded << shifts, axis=1).astype(np.uint32)
          body.append(packed)
          body_len += nwords

        headers[2 * bi] = np.uint32(table_offset) | (np.uint32(bits) << np.uint32(24))
        headers[2 * bi + 1] = np.uint32(values_offset)
        bi += 1

  if body:
    return np.concatenate([headers] + body)
  return headers


def _native_encode_channel(chan: np.ndarray, block_size) -> "np.ndarray | None":
  """C++ fast path (igneous_tpu/native/csrc/cseg.cpp); None → numpy path.
  Stride-aware: Fortran-ordered download cutouts (and sliced views) encode
  in place with no ascontiguousarray copy."""
  import ctypes

  from .native import cseg_lib

  lib = cseg_lib()
  if lib is None:
    return None
  item = chan.dtype.itemsize
  strides = [s // item for s in chan.strides]
  if any(s % item for s in chan.strides) or any(s <= 0 for s in strides):
    chan = np.ascontiguousarray(chan)  # exotic views: normalize first
    strides = [s // item for s in chan.strides]
  out = ctypes.POINTER(ctypes.c_uint32)()
  n = lib.cseg_encode_channel_strided(
    chan.ctypes.data_as(ctypes.c_void_p),
    1 if item == 8 else 0,
    *[int(v) for v in chan.shape],
    *[int(s) for s in strides],
    *[int(b) for b in block_size],
    ctypes.byref(out),
  )
  if n <= 0:
    return None
  try:
    return np.ctypeslib.as_array(out, shape=(n,)).copy()
  finally:
    lib.cseg_free(out)


def _prefers_numpy_encode(chan: np.ndarray, block_size) -> bool:
  """Probe a slab of the interior blocks for the constant-block fraction.
  Uniform-heavy chunks (the realistic mip-pyramid segmentation case)
  encode fastest on the bulk-NumPy compare path — it never visits most
  voxels twice — while dense chunks win on the native per-voxel walk.
  All paths emit identical bytes; this only picks the fastest."""
  sx, sy, sz = chan.shape
  bx, by, bz = [int(b) for b in block_size]
  nbx, nby, nbz = sx // bx, sy // by, sz // bz
  if nbx * nby * nbz == 0:
    return True  # no full interior block: tiny chunk, numpy is fine
  pz = max(nbz // 8, 1)  # ~1/8 z-slab: representative, nearly free
  region = chan[: nbx * bx, : nby * by, : pz * bz]
  six = region.reshape((bx, nbx, by, nby, bz, pz), order="F")
  uni = (six == six[0:1, :, 0:1, :, 0:1, :]).all(axis=(0, 2, 4))
  return float(uni.mean()) >= 0.5


def compress(img: np.ndarray, block_size: Sequence[int] = (8, 8, 8)) -> bytes:
  """img: (x, y, z, c) array of uint32/uint64 (smaller uints are widened)."""
  if img.ndim == 3:
    img = img[..., np.newaxis]
  if img.dtype.itemsize <= 4:
    img = img.astype(np.uint32, copy=False)
  else:
    img = img.astype(np.uint64, copy=False)

  num_channels = img.shape[3]
  channels = []
  offsets = np.zeros(num_channels, dtype=np.uint32)
  pos = num_channels
  for c in range(num_channels):
    chan = img[:, :, :, c]
    enc = None
    if not _prefers_numpy_encode(chan, block_size):
      enc = _native_encode_channel(chan, block_size)
    if enc is None:
      enc = _encode_channel(chan, tuple(int(b) for b in block_size))
    offsets[c] = pos
    pos += len(enc)
    channels.append(enc)
  return np.concatenate([offsets] + channels).tobytes()


def _native_decode_channel(words, shape3, dtype, block_size):
  import ctypes

  from .native import cseg_lib

  lib = cseg_lib()
  if lib is None:
    return None
  words = np.ascontiguousarray(words)
  out = np.empty(shape3, dtype=dtype)
  rc = lib.cseg_decode_channel(
    words.ctypes.data_as(ctypes.c_void_p),
    len(words),
    1 if np.dtype(dtype).itemsize == 8 else 0,
    *[int(v) for v in shape3],
    *[int(b) for b in block_size],
    out.ctypes.data_as(ctypes.c_void_p),
  )
  if rc != 0:
    raise ValueError(f"corrupt compressed_segmentation stream (code {rc})")
  return out


def _corrupt(reason: str):
  # invalid offsets fail loudly instead of silently truncating (the
  # native, vectorized, and loop decoders must behave identically)
  raise ValueError(f"corrupt compressed_segmentation stream ({reason})")


def _stream_words(data) -> np.ndarray:
  """Read-only uint32 view of the stream; a length that is not a whole
  number of words is corruption, reported like every other decode fault."""
  if len(data) % 4:
    _corrupt(f"stream length {len(data)} not a multiple of 4")
  return np.frombuffer(data, dtype=np.uint32)


def _block_constants(words, toff, words_per_entry, work_dtype):
  """Lookup-table entry 0 of each block in ``toff`` (the value of every
  voxel of a bits==0 block)."""
  if words_per_entry == 2:
    lo = words[toff]
    hi = words[toff + 1]
    return lo.astype(np.uint64) | (hi.astype(np.uint64) << np.uint64(32))
  return words[toff].astype(work_dtype, copy=False)


def _decode_channel_np(words, base, shape3, block_size, words_per_entry,
                       work_dtype, out=None):
  """Bulk-NumPy decode of one channel → (sx, sy, sz) F-ordered array of
  ``work_dtype`` (uint32/uint64 matching the table entry width). Offsets
  are validated against the stream bounds exactly like the loop decoder."""
  sx, sy, sz = shape3
  bx, by, bz = block_size
  gx, gy, gz = -(-sx // bx), -(-sy // by), -(-sz // bz)
  nblocks = gx * gy * gz
  total = len(words)
  if out is None:
    out = np.empty((sx, sy, sz), dtype=work_dtype, order="F")
  if nblocks == 0:
    return out
  if base + 2 * nblocks > total:
    _corrupt("header out of range")
  hw = words[base : base + 2 * nblocks].astype(np.int64)
  w0 = hw[0::2]
  w1 = hw[1::2]
  bits_all = w0 >> 24
  bad = ~np.isin(bits_all, VALID_BITS)
  if bool(bad.any()):
    _corrupt(f"invalid bit width {int(bits_all[np.argmax(bad)])}")
  toff_all = base + (w0 & 0xFFFFFF)
  voff_all = base + w1

  for cat in _block_categories((sx, sy, sz), (bx, by, bz)):
    (x0, y0, z0), (cx, cy, cz), (nbx, nby, nbz) = _category_geometry(cat)
    nvox = cx * cy * cz
    bid = _category_bids(cat, (bx, by, bz), gx, gy)
    region = out[x0 : x0 + cx * nbx, y0 : y0 + cy * nby, z0 : z0 + cz * nbz]
    bits_cat = bits_all[bid]

    if bool((bits_cat == 0).all()):
      # constant blocks only (the dominant case on real segmentation):
      # one table-entry gather per block and a broadcast store through a
      # strided 6-axis view — no per-voxel index matrix at all
      if bool((toff_all[bid] + words_per_entry > total).any()):
        _corrupt("lookup table out of range")
      consts = _block_constants(words, toff_all[bid], words_per_entry,
                                work_dtype).astype(work_dtype, copy=False)
      s0, s1, s2 = region.strides
      view = np.lib.stride_tricks.as_strided(
        region, shape=(cx, nbx, cy, nby, cz, nbz),
        strides=(s0, s0 * cx, s1, s1 * cy, s2, s2 * cz),
      )
      view[...] = consts.reshape((nbx, nby, nbz), order="F")[
        None, :, None, :, None, :
      ]
      continue

    cat_vals = np.empty((len(bid), nvox), dtype=work_dtype)
    for b in np.unique(bits_cat):
      b = int(b)
      sel = np.nonzero(bits_cat == b)[0]
      gids = bid[sel]
      if b == 0:
        if bool((toff_all[gids] + words_per_entry > total).any()):
          _corrupt("lookup table out of range")
        cat_vals[sel] = _block_constants(
          words, toff_all[gids], words_per_entry, work_dtype
        ).astype(work_dtype, copy=False)[:, None]
        continue
      vpw = 32 // b
      nwords = -(-nvox // vpw)
      if bool((voff_all[gids] + nwords > total).any()):
        _corrupt("encoded values out of range")
      packed = words[voff_all[gids][:, None] + np.arange(nwords)[None, :]]
      shifts = (np.arange(vpw, dtype=np.uint32) * np.uint32(b))
      mask = np.uint32((1 << b) - 1) if b < 32 else np.uint32(0xFFFFFFFF)
      idx = (
        ((packed[:, :, None] >> shifts[None, None, :]) & mask)
        .reshape(len(sel), nwords * vpw)[:, :nvox]
        .astype(np.int64)
      )
      tlen = (idx.max(axis=1) + 1) * words_per_entry
      if bool((toff_all[gids] + tlen > total).any()):
        _corrupt("lookup table out of range")
      if words_per_entry == 2:
        lo = words[toff_all[gids][:, None] + 2 * idx]
        hi = words[toff_all[gids][:, None] + 2 * idx + 1]
        vals = lo.astype(np.uint64) | (hi.astype(np.uint64) << np.uint64(32))
      else:
        vals = words[toff_all[gids][:, None] + idx].astype(
          work_dtype, copy=False
        )
      cat_vals[sel] = vals
    # rows are (jz,jy,jx)-ordered blocks, columns (vz,vy,vx)-ordered
    # voxels (both x-fastest): undo the encode-side gather
    region[...] = (
      cat_vals.reshape((nbz, nby, nbx, cz, cy, cx))
      .transpose(5, 2, 4, 1, 3, 0)
      .reshape(region.shape, order="F")
    )
  return out


def _all_constant_blocks(words, base, nblocks) -> bool:
  """True when every block header of the channel carries bits==0 — the
  broadcast-fill numpy path then beats even the native per-voxel walk."""
  end = base + 2 * nblocks
  if base < 0 or end > len(words):
    return False  # malformed: let the real decoder raise with context
  return bool((words[base:end:2] >> np.uint32(24) == 0).all())


def decompress(
  data: bytes,
  shape: Sequence[int],
  dtype,
  block_size: Sequence[int] = (8, 8, 8),
) -> np.ndarray:
  """Returns an (x, y, z, c) array of ``dtype``."""
  # read-only view of the input: the decoders never write into the word
  # stream, and the output array is freshly allocated either way — the
  # old bytearray() copy was pure overhead per chunk
  words = _stream_words(data)
  sx, sy, sz, num_channels = [int(v) for v in shape]
  bx, by, bz = [int(b) for b in block_size]
  gx, gy, gz = -(-sx // bx), -(-sy // by), -(-sz // bz)
  nblocks = gx * gy * gz

  dtype = np.dtype(dtype)
  words_per_entry = 2 if dtype.itemsize == 8 else 1
  work_dtype = np.uint64 if words_per_entry == 2 else np.uint32
  out = np.empty(
    (sx, sy, sz, num_channels), dtype=work_dtype, order="F"
  )
  total_words = len(words)
  for c in range(num_channels):
    if c >= total_words:
      _corrupt("missing channel offset")
    base = int(words[c])
    end = int(words[c + 1]) if c + 1 < num_channels else total_words
    chan = None
    # all-constant channels take the broadcast-fill numpy path outright;
    # the native decoder (when present and the dtype width matches its
    # word layout) wins on dense chunks
    if (
      dtype.itemsize in (4, 8)
      and not _all_constant_blocks(words, base, nblocks)
    ):
      chan = _native_decode_channel(
        words[base:end], (sx, sy, sz), work_dtype, (bx, by, bz),
      )
    if chan is not None:
      out[..., c] = chan
    else:
      # each channel slice of the F-ordered output is itself
      # F-contiguous, so the channel decoder fills it in place
      _decode_channel_np(
        words, base, (sx, sy, sz), (bx, by, bz), words_per_entry,
        work_dtype, out=out[..., c],
      )
  return out.astype(dtype, copy=False)


def _decompress_loop(
  data: bytes,
  shape: Sequence[int],
  dtype,
  block_size: Sequence[int] = (8, 8, 8),
) -> np.ndarray:
  """Per-block reference decoder (the executable spec; golden-fixture
  tests pin ``decompress`` against it). Returns (x, y, z, c) ``dtype``."""
  words = _stream_words(data)
  sx, sy, sz, num_channels = [int(v) for v in shape]
  bx, by, bz = [int(b) for b in block_size]
  gx, gy, gz = -(-sx // bx), -(-sy // by), -(-sz // bz)
  dtype = np.dtype(dtype)
  words_per_entry = 2 if dtype.itemsize == 8 else 1

  out = np.zeros((sx, sy, sz, num_channels), dtype=np.uint64)

  total_words = len(words)
  for c in range(num_channels):
    if c >= total_words:
      _corrupt("missing channel offset")
    base = int(words[c])
    bi = 0
    for z0 in range(0, gz * bz, bz):
      for y0 in range(0, gy * by, by):
        for x0 in range(0, gx * bx, bx):
          if base + 2 * bi + 1 >= total_words:
            _corrupt("header out of range")
          w0 = int(words[base + 2 * bi])
          w1 = int(words[base + 2 * bi + 1])
          bits = w0 >> 24
          if bits not in VALID_BITS:
            _corrupt(f"invalid bit width {bits}")
          table_offset = base + (w0 & 0xFFFFFF)
          values_offset = base + w1
          cx = min(bx, sx - x0)
          cy = min(by, sy - y0)
          cz = min(bz, sz - z0)
          n = cx * cy * cz

          if bits == 0:
            idx = np.zeros(n, dtype=np.uint32)
          else:
            vals_per_word = 32 // bits
            nwords = -(-n // vals_per_word)
            if values_offset + nwords > total_words:
              _corrupt("encoded values out of range")
            packed = words[values_offset : values_offset + nwords]
            shifts = (np.arange(vals_per_word, dtype=np.uint32) * np.uint32(bits))
            mask = np.uint32((1 << bits) - 1) if bits < 32 else np.uint32(0xFFFFFFFF)
            unpacked = ((packed[:, None] >> shifts) & mask).reshape(-1)[:n]
            idx = unpacked.astype(np.uint32)

          max_idx = int(idx.max()) if n else 0
          tlen = (max_idx + 1) * words_per_entry
          if table_offset + tlen > total_words:
            _corrupt("lookup table out of range")
          traw = words[table_offset : table_offset + tlen]
          if words_per_entry == 2:
            table = traw[0::2].astype(np.uint64) | (
              traw[1::2].astype(np.uint64) << np.uint64(32)
            )
          else:
            table = traw.astype(np.uint64)

          block = table[idx].reshape((cx, cy, cz), order="F")
          out[x0 : x0 + cx, y0 : y0 + cy, z0 : z0 + cz, c] = block
          bi += 1

  return out.astype(dtype)


def decompress_region(
  data: bytes,
  shape: Sequence[int],
  dtype,
  lo: Sequence[int],
  hi: Sequence[int],
  block_size: Sequence[int] = (8, 8, 8),
  channel: int = 0,
) -> np.ndarray:
  """Decode only the blocks overlapping [lo, hi) → (hi-lo) (x, y, z) array.

  The random-access path that makes compressed_segmentation usable as an
  IN-RAM representation (reference: crackle's lazy per-label reads,
  /root/reference/igneous/tasks/skeleton.py:477-527): per-label masks
  decode O(label bbox) voxels, never the whole cutout.
  """
  words = _stream_words(data)
  sx, sy, sz, num_channels = [int(v) for v in shape]
  bx, by, bz = [int(b) for b in block_size]
  gx, gy, gz = -(-sx // bx), -(-sy // by), -(-sz // bz)
  lo = [max(0, int(v)) for v in lo]
  hi = [min(s, int(v)) for s, v in zip((sx, sy, sz), hi)]
  out = np.zeros(
    (hi[0] - lo[0], hi[1] - lo[1], hi[2] - lo[2]), dtype=dtype
  )
  if out.size == 0:
    return out
  base = int(words[channel])
  is64 = np.dtype(dtype).itemsize == 8

  for bzi in range(lo[2] // bz, -(-hi[2] // bz)):
    for byi in range(lo[1] // by, -(-hi[1] // by)):
      for bxi in range(lo[0] // bx, -(-hi[0] // bx)):
        bidx = bxi + gx * (byi + gy * bzi)
        w0 = int(words[base + 2 * bidx])
        w1 = int(words[base + 2 * bidx + 1])
        table_off = w0 & 0xFFFFFF
        bits = (w0 >> 24) & 0xFF
        dx = min(bx, sx - bxi * bx)
        dy = min(by, sy - byi * by)
        dz = min(bz, sz - bzi * bz)
        nvox = dx * dy * dz
        if bits == 0:
          packed = np.zeros(nvox, dtype=np.int64)
        else:
          vals_per_word = 32 // bits
          nwords = -(-nvox // vals_per_word)
          enc = words[base + w1 : base + w1 + nwords]
          pos = np.arange(nvox)
          packed = (
            (enc[pos // vals_per_word] >> ((pos % vals_per_word) * bits))
            & np.uint32((1 << bits) - 1)
          ).astype(np.int64)
        if is64:
          lo32 = words[base + table_off + 2 * packed]
          hi32 = words[base + table_off + 2 * packed + 1]
          vals = lo32.astype(np.uint64) | (
            hi32.astype(np.uint64) << np.uint64(32)
          )
        else:
          vals = words[base + table_off + packed]
        block = vals.astype(dtype).reshape((dx, dy, dz), order="F")
        x0, y0, z0 = bxi * bx, byi * by, bzi * bz
        src = tuple(
          slice(max(lo[a] - o, 0), min(hi[a] - o, d))
          for a, (o, d) in enumerate(((x0, dx), (y0, dy), (z0, dz)))
        )
        dst = tuple(
          slice(max(o - lo[a], 0), max(o - lo[a], 0) + (s.stop - s.start))
          for a, (o, s) in enumerate(((x0, src[0]), (y0, src[1]), (z0, src[2])))
        )
        out[dst] = block[src]
  return out
