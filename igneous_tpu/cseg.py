"""Neuroglancer ``compressed_segmentation`` codec.

Format (github.com/google/neuroglancer, sliceview/compressed_segmentation):
the chunk is split per channel into a grid of blocks (default 8x8x8). The
file is a sequence of little-endian uint32 words:

  [channel offset table: num_channels words, offset of each channel start]
  per channel:
    [block headers: 2 words per block, x-fastest block order]
       word0 = lookup_table_offset (low 24 bits) | (encoded_bits << 24)
       word1 = encoded_values_offset
       (offsets in uint32 units relative to the channel start)
    [lookup tables + packed encoded values, interleaved as emitted]

Within a block, voxels are enumerated x-fastest over the block extent
*clipped to the chunk bounds*; each voxel stores an ``encoded_bits``-wide
index into the block's lookup table, packed LSB-first into uint32 words.
``encoded_bits`` ∈ {0,1,2,4,8,16,32}. Lookup table entries are uint32 (one
word) or uint64 (two words, low word first) matching the chunk dtype.

Blocks with identical lookup tables may share them; this encoder reuses the
previous block's table when equal (a common win on uniform regions).

The reference pipeline gets this codec from cloud-volume / the
``compressed-segmentation`` C++ package; this is a fresh numpy
implementation. A native C path can be added behind the same API if encode
throughput becomes the bottleneck.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

VALID_BITS = (0, 1, 2, 4, 8, 16, 32)


def _pick_bits(n_distinct: int) -> int:
  need = max(int(np.ceil(np.log2(max(n_distinct, 1)))), 0)
  for b in VALID_BITS:
    if b >= need:
      return b
  raise ValueError(f"Too many distinct values in block: {n_distinct}")


def _encode_channel(chan: np.ndarray, block_size: Tuple[int, int, int]) -> np.ndarray:
  """chan: (sx, sy, sz) array of uint32 or uint64. Returns uint32 words."""
  sx, sy, sz = chan.shape
  bx, by, bz = block_size
  gx, gy, gz = -(-sx // bx), -(-sy // by), -(-sz // bz)
  nblocks = gx * gy * gz

  words_per_entry = 2 if chan.dtype.itemsize == 8 else 1

  headers = np.zeros(nblocks * 2, dtype=np.uint32)
  body: list = []  # list of uint32 arrays appended after the headers
  body_len = 0
  prev_table = None
  prev_table_offset = 0

  bi = 0
  for z0 in range(0, gz * bz, bz):
    for y0 in range(0, gy * by, by):
      for x0 in range(0, gx * bx, bx):
        block = chan[x0 : min(x0 + bx, sx), y0 : min(y0 + by, sy), z0 : min(z0 + bz, sz)]
        # x-fastest flattening == Fortran order for an (x,y,z) array
        flat = block.reshape(-1, order="F")
        table, idx = np.unique(flat, return_inverse=True)
        bits = _pick_bits(len(table))

        if (
          prev_table is not None
          and len(prev_table) == len(table)
          and np.array_equal(prev_table, table)
        ):
          table_offset = prev_table_offset
        else:
          table_offset = 2 * nblocks + body_len
          if words_per_entry == 2:
            t64 = table.astype(np.uint64)
            tw = np.empty(len(t64) * 2, dtype=np.uint32)
            tw[0::2] = (t64 & np.uint64(0xFFFFFFFF)).astype(np.uint32)
            tw[1::2] = (t64 >> np.uint64(32)).astype(np.uint32)
          else:
            tw = table.astype(np.uint32)
          body.append(tw)
          body_len += len(tw)
          prev_table = table
          prev_table_offset = table_offset

        if table_offset >= (1 << 24):
          raise ValueError("lookup table offset exceeds 24 bits; use smaller chunks")

        values_offset = 2 * nblocks + body_len
        if bits > 0:
          n = len(idx)
          vals_per_word = 32 // bits
          nwords = -(-n // vals_per_word)
          padded = np.zeros(nwords * vals_per_word, dtype=np.uint32)
          padded[:n] = idx.astype(np.uint32)
          padded = padded.reshape(nwords, vals_per_word)
          shifts = (np.arange(vals_per_word, dtype=np.uint32) * np.uint32(bits))
          packed = np.bitwise_or.reduce(padded << shifts, axis=1).astype(np.uint32)
          body.append(packed)
          body_len += nwords

        headers[2 * bi] = np.uint32(table_offset) | (np.uint32(bits) << np.uint32(24))
        headers[2 * bi + 1] = np.uint32(values_offset)
        bi += 1

  if body:
    return np.concatenate([headers] + body)
  return headers


def _native_encode_channel(chan: np.ndarray, block_size) -> "np.ndarray | None":
  """C++ fast path (igneous_tpu/native/csrc/cseg.cpp); None → numpy path.
  Stride-aware: Fortran-ordered download cutouts (and sliced views) encode
  in place with no ascontiguousarray copy."""
  import ctypes

  from .native import cseg_lib

  lib = cseg_lib()
  if lib is None:
    return None
  item = chan.dtype.itemsize
  strides = [s // item for s in chan.strides]
  if any(s % item for s in chan.strides) or any(s <= 0 for s in strides):
    chan = np.ascontiguousarray(chan)  # exotic views: normalize first
    strides = [s // item for s in chan.strides]
  out = ctypes.POINTER(ctypes.c_uint32)()
  n = lib.cseg_encode_channel_strided(
    chan.ctypes.data_as(ctypes.c_void_p),
    1 if item == 8 else 0,
    *[int(v) for v in chan.shape],
    *[int(s) for s in strides],
    *[int(b) for b in block_size],
    ctypes.byref(out),
  )
  if n <= 0:
    return None
  try:
    return np.ctypeslib.as_array(out, shape=(n,)).copy()
  finally:
    lib.cseg_free(out)


def compress(img: np.ndarray, block_size: Sequence[int] = (8, 8, 8)) -> bytes:
  """img: (x, y, z, c) array of uint32/uint64 (smaller uints are widened)."""
  if img.ndim == 3:
    img = img[..., np.newaxis]
  if img.dtype.itemsize <= 4:
    img = img.astype(np.uint32, copy=False)
  else:
    img = img.astype(np.uint64, copy=False)

  num_channels = img.shape[3]
  channels = []
  offsets = np.zeros(num_channels, dtype=np.uint32)
  pos = num_channels
  for c in range(num_channels):
    enc = _native_encode_channel(img[:, :, :, c], block_size)
    if enc is None:
      enc = _encode_channel(img[:, :, :, c], tuple(int(b) for b in block_size))
    offsets[c] = pos
    pos += len(enc)
    channels.append(enc)
  return np.concatenate([offsets] + channels).tobytes()


def _native_decode_channel(words, shape3, dtype, block_size):
  import ctypes

  from .native import cseg_lib

  lib = cseg_lib()
  if lib is None:
    return None
  words = np.ascontiguousarray(words)
  out = np.empty(shape3, dtype=dtype)
  rc = lib.cseg_decode_channel(
    words.ctypes.data_as(ctypes.c_void_p),
    len(words),
    1 if np.dtype(dtype).itemsize == 8 else 0,
    *[int(v) for v in shape3],
    *[int(b) for b in block_size],
    out.ctypes.data_as(ctypes.c_void_p),
  )
  if rc != 0:
    raise ValueError(f"corrupt compressed_segmentation stream (code {rc})")
  return out


def decompress(
  data: bytes,
  shape: Sequence[int],
  dtype,
  block_size: Sequence[int] = (8, 8, 8),
) -> np.ndarray:
  """Returns an (x, y, z, c) array of ``dtype``."""
  words = np.frombuffer(bytearray(data), dtype=np.uint32)
  sx, sy, sz, num_channels = [int(v) for v in shape]
  bx, by, bz = [int(b) for b in block_size]

  # native fast path decodes whole channels; needs a word dtype matching
  # the output dtype width (uint32/uint64)
  if np.dtype(dtype).itemsize in (4, 8):
    native_dtype = np.uint64 if np.dtype(dtype).itemsize == 8 else np.uint32
    outs = []
    ok = True
    for c in range(num_channels):
      start = int(words[c])
      end = int(words[c + 1]) if c + 1 < num_channels else len(words)
      chan = _native_decode_channel(
        words[start:end] if c + 1 < num_channels else words[start:],
        (sx, sy, sz), native_dtype, (bx, by, bz),
      )
      if chan is None:
        ok = False
        break
      outs.append(chan)
    if ok:
      return np.stack(outs, axis=-1).astype(dtype)
  gx, gy, gz = -(-sx // bx), -(-sy // by), -(-sz // bz)
  dtype = np.dtype(dtype)
  words_per_entry = 2 if dtype.itemsize == 8 else 1

  out = np.zeros((sx, sy, sz, num_channels), dtype=np.uint64)

  def corrupt(reason: str):
    # mirror the native decoder: invalid offsets fail loudly instead of
    # silently truncating (the two paths must behave identically)
    raise ValueError(f"corrupt compressed_segmentation stream ({reason})")

  total_words = len(words)
  for c in range(num_channels):
    if c >= total_words:
      corrupt("missing channel offset")
    base = int(words[c])
    bi = 0
    for z0 in range(0, gz * bz, bz):
      for y0 in range(0, gy * by, by):
        for x0 in range(0, gx * bx, bx):
          if base + 2 * bi + 1 >= total_words:
            corrupt("header out of range")
          w0 = int(words[base + 2 * bi])
          w1 = int(words[base + 2 * bi + 1])
          bits = w0 >> 24
          if bits not in VALID_BITS:
            corrupt(f"invalid bit width {bits}")
          table_offset = base + (w0 & 0xFFFFFF)
          values_offset = base + w1
          cx = min(bx, sx - x0)
          cy = min(by, sy - y0)
          cz = min(bz, sz - z0)
          n = cx * cy * cz

          if bits == 0:
            idx = np.zeros(n, dtype=np.uint32)
          else:
            vals_per_word = 32 // bits
            nwords = -(-n // vals_per_word)
            if values_offset + nwords > total_words:
              corrupt("encoded values out of range")
            packed = words[values_offset : values_offset + nwords]
            shifts = (np.arange(vals_per_word, dtype=np.uint32) * np.uint32(bits))
            mask = np.uint32((1 << bits) - 1) if bits < 32 else np.uint32(0xFFFFFFFF)
            unpacked = ((packed[:, None] >> shifts) & mask).reshape(-1)[:n]
            idx = unpacked.astype(np.uint32)

          max_idx = int(idx.max()) if n else 0
          tlen = (max_idx + 1) * words_per_entry
          if table_offset + tlen > total_words:
            corrupt("lookup table out of range")
          traw = words[table_offset : table_offset + tlen]
          if words_per_entry == 2:
            table = traw[0::2].astype(np.uint64) | (
              traw[1::2].astype(np.uint64) << np.uint64(32)
            )
          else:
            table = traw.astype(np.uint64)

          block = table[idx].reshape((cx, cy, cz), order="F")
          out[x0 : x0 + cx, y0 : y0 + cy, z0 : z0 + cz, c] = block
          bi += 1

  return out.astype(dtype)


def decompress_region(
  data: bytes,
  shape: Sequence[int],
  dtype,
  lo: Sequence[int],
  hi: Sequence[int],
  block_size: Sequence[int] = (8, 8, 8),
  channel: int = 0,
) -> np.ndarray:
  """Decode only the blocks overlapping [lo, hi) → (hi-lo) (x, y, z) array.

  The random-access path that makes compressed_segmentation usable as an
  IN-RAM representation (reference: crackle's lazy per-label reads,
  /root/reference/igneous/tasks/skeleton.py:477-527): per-label masks
  decode O(label bbox) voxels, never the whole cutout.
  """
  words = np.frombuffer(bytearray(data), dtype=np.uint32)
  sx, sy, sz, num_channels = [int(v) for v in shape]
  bx, by, bz = [int(b) for b in block_size]
  gx, gy, gz = -(-sx // bx), -(-sy // by), -(-sz // bz)
  lo = [max(0, int(v)) for v in lo]
  hi = [min(s, int(v)) for s, v in zip((sx, sy, sz), hi)]
  out = np.zeros(
    (hi[0] - lo[0], hi[1] - lo[1], hi[2] - lo[2]), dtype=dtype
  )
  if out.size == 0:
    return out
  base = int(words[channel])
  is64 = np.dtype(dtype).itemsize == 8

  for bzi in range(lo[2] // bz, -(-hi[2] // bz)):
    for byi in range(lo[1] // by, -(-hi[1] // by)):
      for bxi in range(lo[0] // bx, -(-hi[0] // bx)):
        bidx = bxi + gx * (byi + gy * bzi)
        w0 = int(words[base + 2 * bidx])
        w1 = int(words[base + 2 * bidx + 1])
        table_off = w0 & 0xFFFFFF
        bits = (w0 >> 24) & 0xFF
        dx = min(bx, sx - bxi * bx)
        dy = min(by, sy - byi * by)
        dz = min(bz, sz - bzi * bz)
        nvox = dx * dy * dz
        if bits == 0:
          packed = np.zeros(nvox, dtype=np.int64)
        else:
          vals_per_word = 32 // bits
          nwords = -(-nvox // vals_per_word)
          enc = words[base + w1 : base + w1 + nwords]
          pos = np.arange(nvox)
          packed = (
            (enc[pos // vals_per_word] >> ((pos % vals_per_word) * bits))
            & np.uint32((1 << bits) - 1)
          ).astype(np.int64)
        if is64:
          lo32 = words[base + table_off + 2 * packed]
          hi32 = words[base + table_off + 2 * packed + 1]
          vals = lo32.astype(np.uint64) | (
            hi32.astype(np.uint64) << np.uint64(32)
          )
        else:
          vals = words[base + table_off + packed]
        block = vals.astype(dtype).reshape((dx, dy, dz), order="F")
        x0, y0, z0 = bxi * bx, byi * by, bzi * bz
        src = tuple(
          slice(max(lo[a] - o, 0), min(hi[a] - o, d))
          for a, (o, d) in enumerate(((x0, dx), (y0, dy), (z0, dz)))
        )
        dst = tuple(
          slice(max(o - lo[a], 0), max(o - lo[a], 0) + (s.stop - s.start))
          for a, (o, s) in enumerate(((x0, src[0]), (y0, src[1]), (z0, src[2])))
        )
        out[dst] = block[src]
  return out
