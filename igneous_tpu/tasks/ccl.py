"""Whole-image connected components labeling — the 4-pass protocol.

Reference parity: /root/reference/igneous/tasks/image/ccl.py
  pass 1 CCLFacesTask        (:126-194)  local CCL → store 3 back faces
  pass 2 CCLEquivalancesTask (:196-294)  link faces of adjacent tasks
  pass 3 create_relabeling   (:358-420)  single-machine global union-find
  pass 4 RelabelCCLTask      (:296-356)  recompute + remap + write dest

Key invariants kept from the reference design:
  - every pass recomputes the identical deterministic local CCL
    (ops.ccl.connected_components is deterministic);
  - label offsets are task_num * voxels_per_cutout so local ids never
    collide globally (reference ccl.py:75-87);
  - cross-task data flows through the object store only (faces,
    equivalence JSONs, relabel maps) — no network collectives;
  - the +1 overlap cutout is blacked out on its "rails" (voxels extended
    in ≥2 axes) so 6-connectivity merges are exactly the ones face planes
    witness (reference ccl.py:103-124).

The local CCL itself runs on device (pointer-doubling label propagation).
"""

from __future__ import annotations

import gzip
import io
import json
from typing import Optional, Sequence, Tuple

import numpy as np

from ..lib import Bbox, Vec, jsonify
from ..queues.registry import RegisteredTask
from ..storage import CloudFiles
from ..volume import Volume
from ..ops.ccl import DisjointSet, connected_components, threshold_image
from ..ops import remap as fastremap


def _npy_bytes(arr: np.ndarray) -> bytes:
  from ..storage import scratch_gzip_level

  buf = io.BytesIO()
  np.save(buf, arr)
  # face planes are scratch (pass-2 consumes, gc deletes): level follows
  # IGNEOUS_SCRATCH_COMPRESS; the historical 4 holds when unset
  return gzip.compress(
    buf.getvalue(), compresslevel=scratch_gzip_level(4), mtime=0
  )


def _npy_load(data: bytes) -> np.ndarray:
  return np.load(io.BytesIO(gzip.decompress(data)))


def ccl_scratch_path(dest_path: str, mip: int) -> str:
  return f"ccl/{mip}"


def label_offset(task_num: int, shape: Sequence[int]) -> int:
  """Task-local → global label offset: task_num × cutout voxels
  (cutout = shape + 1 overlap; reference ccl.py:75-87)."""
  vox = int(np.prod(np.asarray(shape, dtype=np.int64) + 1))
  return task_num * vox


def _download_and_ccl(
  src_path: str,
  mip: int,
  shape: Vec,
  offset: Vec,
  task_num: int,
  fill_missing: bool,
  threshold_gte: Optional[float],
  threshold_lte: Optional[float],
  dust_threshold: int = 0,
) -> Tuple[np.ndarray, Bbox, Bbox]:
  """The deterministic shared pass: cutout+1 → threshold → rails blackout
  → dust → device CCL → +offset. Returns (labels_u64, cutout_bbox,
  core_bbox)."""
  img, cutout, core = _prep_ccl_image(
    src_path, mip, shape, offset, fill_missing, threshold_gte, threshold_lte,
    dust_threshold,
  )
  cc = connected_components(img)
  return _offset_components(cc, task_num, shape), cutout, core


def _prep_ccl_image(
  src_path, mip, shape, offset, fill_missing, threshold_gte, threshold_lte,
  dust_threshold: int = 0,
) -> Tuple[np.ndarray, Bbox, Bbox]:
  """Download + threshold + rails blackout (everything before the CCL
  kernel) — the batched driver runs this per task and dispatches the CCL
  for a whole batch at once."""
  vol = Volume(src_path, mip=mip, fill_missing=fill_missing, bounded=False)
  bounds = vol.meta.bounds(mip)
  core = Bbox.intersection(Bbox(offset, offset + shape), bounds)
  cutout = Bbox.intersection(Bbox(offset, offset + shape + 1), bounds)

  img = vol.download(cutout)[..., 0]
  img = threshold_image(img, threshold_gte, threshold_lte)

  # rails blackout: voxels extended past the core in ≥2 axes
  ext_counts = np.zeros(img.shape, dtype=np.uint8)
  for axis in range(3):
    if cutout.maxpt[axis] > core.maxpt[axis]:
      sl = [slice(None)] * 3
      sl[axis] = slice(int(core.maxpt[axis] - cutout.minpt[axis]), None)
      ext = np.zeros(img.shape, dtype=np.uint8)
      ext[tuple(sl)] = 1
      ext_counts += ext
  img[ext_counts >= 2] = 0
  if dust_threshold:
    # dust BEFORE the CCL so every pass recomputes identical labels
    # (reference ccl.py:167-171)
    from ..ops.ccl import dust

    img = dust(img, dust_threshold, connectivity=6, in_place=True)
  return img, cutout, core


def _offset_components(cc: np.ndarray, task_num: int, shape) -> np.ndarray:
  cc = cc.astype(np.uint64)
  cc[cc != 0] += np.uint64(label_offset(task_num, shape))
  return cc


def store_ccl_faces(cc, cutout, core, task_num, cf, scratch):
  """Upload the 3 overlap ('back') face planes (pass-1 output format)."""
  for axis, name in enumerate("xyz"):
    if cutout.maxpt[axis] > core.maxpt[axis]:
      sl = [slice(None)] * 3
      sl[axis] = int(cutout.size3()[axis]) - 1
      cf.put(
        f"{scratch}/faces/{task_num}-{name}.npy.gz",
        _npy_bytes(cc[tuple(sl)]),
      )


class CCLFacesTask(RegisteredTask):
  """Pass 1: per-task CCL; store the 3 overlap ('back') face planes."""

  def __init__(
    self,
    src_path: str,
    mip: int,
    shape: Sequence[int],
    offset: Sequence[int],
    task_num: int,
    fill_missing: bool = False,
    threshold_gte: Optional[float] = None,
    threshold_lte: Optional[float] = None,
    dust_threshold: int = 0,
  ):
    self.src_path = src_path
    self.mip = int(mip)
    self.shape = Vec(*shape)
    self.offset = Vec(*offset)
    self.task_num = int(task_num)
    self.fill_missing = fill_missing
    self.threshold_gte = threshold_gte
    self.threshold_lte = threshold_lte
    self.dust_threshold = int(dust_threshold)

  def execute(self):
    cc, cutout, core = _download_and_ccl(
      self.src_path, self.mip, self.shape, self.offset, self.task_num,
      self.fill_missing, self.threshold_gte, self.threshold_lte,
      self.dust_threshold,
    )
    store_ccl_faces(
      cc, cutout, core, self.task_num, CloudFiles(self.src_path),
      ccl_scratch_path(self.src_path, self.mip),
    )


class CCLEquivalancesTask(RegisteredTask):
  """Pass 2: recompute local CCL; link first planes against the previous
  task's stored back faces; emit (all local labels, equivalence pairs)."""

  def __init__(
    self,
    src_path: str,
    mip: int,
    shape: Sequence[int],
    offset: Sequence[int],
    task_num: int,
    grid_size: Sequence[int],
    fill_missing: bool = False,
    threshold_gte: Optional[float] = None,
    threshold_lte: Optional[float] = None,
    dust_threshold: int = 0,
  ):
    self.src_path = src_path
    self.mip = int(mip)
    self.shape = Vec(*shape)
    self.offset = Vec(*offset)
    self.task_num = int(task_num)
    self.grid_size = Vec(*grid_size)
    self.fill_missing = fill_missing
    self.threshold_gte = threshold_gte
    self.threshold_lte = threshold_lte
    self.dust_threshold = int(dust_threshold)

  def execute(self):
    cc, cutout, core = _download_and_ccl(
      self.src_path, self.mip, self.shape, self.offset, self.task_num,
      self.fill_missing, self.threshold_gte, self.threshold_lte,
      self.dust_threshold,
    )
    cf = CloudFiles(self.src_path)
    scratch = ccl_scratch_path(self.src_path, self.mip)
    gx, gy, gz = (int(v) for v in self.grid_size)
    coord = (
      self.task_num % gx,
      (self.task_num // gx) % gy,
      self.task_num // (gx * gy),
    )
    strides = (1, gx, gx * gy)

    pairs = set()
    for axis, name in enumerate("xyz"):
      if coord[axis] == 0:
        continue
      neighbor = self.task_num - strides[axis]
      data = cf.get(f"{scratch}/faces/{neighbor}-{name}.npy.gz")
      if data is None:
        continue
      their_face = _npy_load(data)
      sl = [slice(None)] * 3
      sl[axis] = 0  # our first plane == their stored overlap plane
      my_face = cc[tuple(sl)]
      if their_face.shape != my_face.shape:
        # dataset-edge clamping can shave a row; compare the intersection
        mins = tuple(min(a, b) for a, b in zip(their_face.shape, my_face.shape))
        their_face = their_face[: mins[0], : mins[1]]
        my_face = my_face[: mins[0], : mins[1]]
      icm = fastremap.inverse_component_map(my_face, their_face)
      for mine, theirs in icm.items():
        for t in theirs.tolist():
          pairs.add((int(mine), int(t)))

    labels = [int(v) for v in np.unique(cc) if v != 0]
    cf.put_json(
      f"{scratch}/equivalences/{self.task_num}.json",
      {"labels": labels, "pairs": sorted(pairs)},
    )


def create_relabeling(src_path: str, mip: int = 0, shape=None) -> int:
  """Pass 3 (single machine, reference ccl.py:358-420): global union-find
  over all equivalence files → per-task relabel maps + max_label.json.
  Returns the final component count. ``shape`` is accepted for signature
  parity with the reference; the equivalence listing already determines
  the grid here."""
  del shape
  cf = CloudFiles(src_path)
  scratch = ccl_scratch_path(src_path, mip)
  ds = DisjointSet()
  task_labels = {}  # task_num -> [labels]
  for key in cf.list(f"{scratch}/equivalences/"):
    doc = cf.get_json(key)
    task_num = int(key.split("/")[-1].split(".")[0])
    task_labels[task_num] = doc["labels"]
    for lbl in doc["labels"]:
      ds.makeset(lbl)
    for a, b in doc["pairs"]:
      ds.union(a, b)

  mapping, max_label = ds.renumber(start=1)
  for task_num, labels in task_labels.items():
    cf.put_json(
      f"{scratch}/relabel/{task_num}.json",
      {str(lbl): mapping[lbl] for lbl in labels},
    )
  cf.put_json(f"{scratch}/max_label.json", {"max_label": max_label})
  return max_label


class RelabelCCLTask(RegisteredTask):
  """Pass 4: recompute local CCL, apply the global relabel map, crop the
  overlap, and write the destination segmentation."""

  def __init__(
    self,
    src_path: str,
    dest_path: str,
    mip: int,
    shape: Sequence[int],
    offset: Sequence[int],
    task_num: int,
    fill_missing: bool = False,
    threshold_gte: Optional[float] = None,
    threshold_lte: Optional[float] = None,
    dust_threshold: int = 0,
  ):
    self.src_path = src_path
    self.dest_path = dest_path
    self.mip = int(mip)
    self.shape = Vec(*shape)
    self.offset = Vec(*offset)
    self.task_num = int(task_num)
    self.fill_missing = fill_missing
    self.threshold_gte = threshold_gte
    self.threshold_lte = threshold_lte
    self.dust_threshold = int(dust_threshold)

  def execute(self):
    cc, cutout, core = _download_and_ccl(
      self.src_path, self.mip, self.shape, self.offset, self.task_num,
      self.fill_missing, self.threshold_gte, self.threshold_lte,
      self.dust_threshold,
    )
    cf = CloudFiles(self.src_path)
    scratch = ccl_scratch_path(self.src_path, self.mip)
    table = cf.get_json(f"{scratch}/relabel/{self.task_num}.json")
    if table is None:
      raise FileNotFoundError(
        f"No relabel map for task {self.task_num}; run create_relabeling"
      )
    table = {np.uint64(k): np.uint64(v) for k, v in table.items()}
    table[np.uint64(0)] = np.uint64(0)
    out = fastremap.remap(cc, table)

    sl = tuple(
      slice(int(a), int(b))
      for a, b in zip(core.minpt - cutout.minpt, core.maxpt - cutout.minpt)
    )
    dest = Volume(self.dest_path, mip=self.mip)
    dest.upload(core, out[sl].astype(dest.dtype))
