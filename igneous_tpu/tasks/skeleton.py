"""Skeleton forge + merge tasks.

Reference parity: /root/reference/igneous/tasks/skeleton.py
  SkeletonTask (:54-808): per-cutout TEASAR skeletonization with a
  1-voxel overlap and pinned border targets so stage-2 merges weld
  trivially; dust/object_ids masking; sharded `.frags` or individual
  fragment files; spatial index.
  UnshardedSkeletonMergeTask (:810-916), ShardedSkeletonMergeTask
  (:918-1072), transfer/delete (:1132-1156).

TPU-first: the whole-cutout multilabel EDT is one device program
(ops.edt); Dijkstra tracing stays host (the reference's own split).
Border pinning is geometric (shared-plane contact-patch centroids) so the
pinned vertex is identical on both sides of a task boundary.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..lib import Bbox, Vec
from ..queues.registry import RegisteredTask, queueable
from ..storage import CloudFiles
from ..volume import Volume
from ..mesh_io import FragMap
from ..ops import remap as fastremap
from ..ops.skeletonize import TeasarParams, skeletonize
from ..skeleton_io import DEFAULT_ATTRIBUTES, Skeleton, postprocess
from ..spatial_index import SpatialIndex


def skel_dir_for(vol: Volume, skel_dir: Optional[str]) -> str:
  if skel_dir:
    return skel_dir
  if vol.info.get("skeletons"):
    return vol.info["skeletons"]
  raise ValueError("No skeleton directory configured in the info file")


def border_targets(
  labels: np.ndarray, core_shape, low_sides=(False, False, False)
) -> Dict[int, np.ndarray]:
  """Deterministic pinned voxels per label on every shared boundary plane.

  A task's high-side +1 overlap plane is the SAME global plane as its
  neighbor's first core plane, so both tasks compute the pin from
  identical plane content: each label patch's member voxel nearest the
  patch centroid. Their skeletons gain a common vertex and stage-2
  consolidation welds them. ``low_sides[axis]`` is True when a neighbor
  task exists below (pin plane index 0); the high plane at index
  core_shape[axis] is pinned whenever the cutout includes it."""
  from ..ops.ccl import _ccl_native

  out: Dict[int, List[np.ndarray]] = defaultdict(list)
  for axis in range(3):
    planes = []
    if core_shape[axis] < labels.shape[axis]:
      planes.append(core_shape[axis])  # high-side overlap plane
    if low_sides[axis]:
      planes.append(0)  # low-side shared plane
    for plane_idx in planes:
      sl = [slice(None)] * 3
      sl[axis] = plane_idx
      plane = labels[tuple(sl)]
      # ONE multilabel CC per plane instead of one label() per label:
      # a 1-thick 6-connected slab is exactly in-plane 4-connectivity,
      # and multilabel components equal the per-label binary components.
      # This is host-side pin bookkeeping on tiny planes — NEVER dispatch
      # it to the device CCL kernel (a per-plane XLA compile would
      # dominate the task); use the native host kernel or scipy.
      got = _ccl_native(np.ascontiguousarray(plane[:, :, None]), 6)
      others = [a for a in range(3) if a != axis]
      if got is None:
        # no toolchain: per-label scipy labeling (the original path)
        from scipy import ndimage

        for label in np.unique(plane):
          if label == 0:
            continue
          patch, n = ndimage.label(plane == label)
          for comp in range(1, n + 1):
            pts = np.argwhere(patch == comp)
            centroid = pts.mean(axis=0)
            nearest = pts[np.argmin(((pts - centroid) ** 2).sum(axis=1))]
            coord = np.zeros(3, dtype=np.int64)
            coord[axis] = plane_idx
            coord[others[0]] = nearest[0]
            coord[others[1]] = nearest[1]
            out[int(label)].append(coord)
        continue
      comps = got[0][:, :, 0]
      flat = comps.ravel()
      fg = np.flatnonzero(flat)
      if len(fg) == 0:
        continue
      order = fg[np.argsort(flat[fg], kind="stable")]
      sorted_c = flat[order]
      starts = np.flatnonzero(
        np.concatenate([[True], sorted_c[1:] != sorted_c[:-1]])
      )
      ends = np.concatenate([starts[1:], [len(order)]])
      w = plane.shape[1]
      plane_flat = plane.ravel()
      for s, e in zip(starts, ends):
        members = order[s:e]
        pts = np.stack([members // w, members % w], axis=1)
        centroid = pts.mean(axis=0)
        nearest = pts[np.argmin(((pts - centroid) ** 2).sum(axis=1))]
        coord = np.zeros(3, dtype=np.int64)
        coord[axis] = plane_idx
        coord[others[0]] = nearest[0]
        coord[others[1]] = nearest[1]
        out[int(plane_flat[members[0]])].append(coord)
  return {k: np.stack(v) for k, v in out.items()}


class SkeletonTask(RegisteredTask):
  def __init__(
    self,
    cloudpath: str,
    shape: Sequence[int],
    offset: Sequence[int],
    mip: int = 0,
    teasar_params: Optional[dict] = None,
    object_ids: Optional[Sequence[int]] = None,
    mask_ids: Optional[Sequence[int]] = None,
    dust_threshold: int = 1000,
    dust_global: bool = False,
    fill_missing: bool = False,
    sharded: bool = False,
    skel_dir: Optional[str] = None,
    spatial_index: bool = True,
    fix_borders: bool = True,
    fill_holes: int = 0,
    fix_branching: bool = True,
    fix_avocados: bool = False,
    fix_autapses: bool = False,
    cross_sectional_area: bool = False,
    csa_smoothing_window: int = 1,
    csa_repair_sec_per_label: int = -1,
    low_memory_csa: bool = False,
    extra_targets: Optional[Dict] = None,
    parallel: int = 1,
    timestamp: Optional[float] = None,
    frag_path: Optional[str] = None,
    root_ids_cloudpath: Optional[str] = None,
  ):
    self.cloudpath = cloudpath
    self.shape = Vec(*shape)
    self.offset = Vec(*offset)
    self.mip = int(mip)
    self.teasar_params = teasar_params or {}
    self.object_ids = list(object_ids) if object_ids else None
    self.mask_ids = list(mask_ids) if mask_ids else None
    self.dust_threshold = int(dust_threshold)
    self.dust_global = bool(dust_global)
    self.fill_missing = fill_missing
    self.sharded = sharded
    self.skel_dir = skel_dir
    self.spatial_index = spatial_index
    self.fix_borders = fix_borders
    # hole-filling aggressiveness ladder (reference --fill-holes int:
    # 0 off, 1 fill cavities, 2 +fix borders, 3 +morphological closing);
    # bool True from older payloads means level 1
    self.fill_holes = int(fill_holes)
    self.fix_branching = bool(fix_branching)
    self.fix_avocados = bool(fix_avocados)
    # reference --fix-autapses (cli.py:1274): graphene-only, opt-in —
    # constrains TEASAR to the chunk graph's connectivity
    self.fix_autapses = bool(fix_autapses)
    self.cross_sectional_area = bool(cross_sectional_area)
    # moving-average window over slice normals (reference kimimaro
    # cross_sectional_area smoothing_window, tasks/skeleton.py:449-457)
    self.csa_smoothing_window = int(csa_smoothing_window)
    # per-label repair time budget in seconds: -1 unlimited, 0 disables
    # the contact-repair pass (reference --cross-section-label-repair-sec,
    # cli.py:1290 — its default is 0/off; ours stays -1/on)
    self.csa_repair_sec_per_label = int(csa_repair_sec_per_label)
    self.low_memory_csa = bool(low_memory_csa)
    # {label: [[x,y,z(,swc_label)] global voxel coords]} — synapse/marker
    # points that must become skeleton vertices, optionally typed for SWC
    # export (reference synapse kD-tree targets,
    # task_creation/skeleton.py:390-411)
    self.extra_targets = {
      int(k): [
        [int(p[0]), int(p[1]), int(p[2]), int(p[3]) if len(p) > 3 else 0]
        for p in v
      ]
      for k, v in (extra_targets or {}).items()
    }
    self.parallel = int(parallel)
    self.timestamp = timestamp
    # write stage-1 fragments/spatial cells to a different bucket
    # (reference --output/frag_path, tasks/skeleton.py frag_path)
    self.frag_path = frag_path
    # materialized root-id layer: cheaper than graphene server lookups
    # (reference --root-ids, cli.py:1293)
    self.root_ids_cloudpath = root_ids_cloudpath

  def _apply_global_dust(self, labels: np.ndarray) -> np.ndarray:
    from .stats import globally_small_labels

    small = globally_small_labels(
      self.cloudpath, self.mip, fastremap.unique(labels),
      self.dust_threshold,
    )
    if small:
      labels = fastremap.mask(labels, small)
    return labels

  # context margin for cross-section contact repair (voxels): the
  # reference re-downloads ±150vx around flagged vertices
  # (tasks/skeleton.py:84,406-410)
  CSA_REPAIR_CONTEXT = 150

  def _repair_csa_contacts(self, vol: "Volume", skels, bounds: Bbox) -> None:
    """Revisit vertices whose slice was clipped by the cutout (negative
    areas): cluster them, re-download each cluster's neighborhood with
    context, recompute exactly, and overwrite where the larger view
    produced a clean slice (reference tasks/skeleton.py:574-720 —
    DBSCAN-clustered boundary-contact repair)."""
    from ..ops.cross_section import cross_sectional_area as _csa
    from ..ops.dbscan import dbscan

    import time as _time

    anis = np.asarray(vol.resolution, dtype=np.float32)
    ctx = self.CSA_REPAIR_CONTEXT
    eps = float(2 * ctx * anis.min())  # one download per nearby group
    budget = self.csa_repair_sec_per_label
    for label, skel in skels.items():
      deadline = _time.monotonic() + budget if budget > 0 else None
      areas = skel.extra_attributes.get("cross_sectional_area")
      if areas is None or not len(skel.vertices):
        continue
      # clipped slices carry -area; exactly -1.0 is the unrepairable
      # sentinel (vertex outside mask / zero tangent) — re-downloading
      # cannot fix those, so skip them
      bad = np.flatnonzero((areas < 0) & (areas != -1.0))
      if len(bad) == 0:
        continue
      clusters = dbscan(skel.vertices[bad], eps=eps, min_samples=1)
      for c in np.unique(clusters):
        if deadline is not None and _time.monotonic() > deadline:
          break  # per-label budget spent; remaining flags stay negative
        members = bad[clusters == c]
        vox = np.round(
          skel.vertices[members] / anis
        ).astype(np.int64)
        region = Bbox(vox.min(axis=0) - ctx, vox.max(axis=0) + ctx + 1)
        region = Bbox.intersection(region, bounds)
        if region.empty():
          continue
        if vol.graphene is not None:
          # the skeletons are keyed by proofread ROOT ids — a raw
          # download would yield supervoxels and an always-empty mask
          cut = vol.download(
            region, agglomerate=True, timestamp=self.timestamp
          )[..., 0]
        else:
          cut = vol.download(region)[..., 0]
        if self.fill_holes:
          # same mask semantics as the original pass (execute fills holes
          # before measuring); an unfilled cavity would shrink repaired
          # areas relative to unflagged neighbors
          from ..ops.morphology import fill_holes as _fill_holes

          cut = _fill_holes(cut, level=self.fill_holes)
        mask = np.ascontiguousarray(cut == label)
        vmask = np.zeros(len(skel.vertices), dtype=bool)
        vmask[members] = True
        repaired = _csa(
          mask, skel, anisotropy=tuple(float(v) for v in anis),
          offset=tuple(float(v) for v in region.minpt),
          window=ctx, vertex_mask=vmask,
          smoothing_window=self.csa_smoothing_window,
        )
        # a clean (positive) recompute wins — but the full slice always
        # CONTAINS the clipped slice, so a repaired area below the
        # flagged lower bound means the repair view diverged (e.g. a
        # cavity that the original whole-cutout fill_holes closed but the
        # ±ctx crop leaves open at its border); reject those rather than
        # silently shrink. A still-negative recompute means the section
        # genuinely reaches the dataset boundary — keep whichever lower
        # bound is larger.
        m = members
        accept = (repaired[m] > 0) & (
          repaired[m] >= -areas[m] * (1.0 - 1e-6)
        )
        areas[m] = np.where(
          accept, repaired[m], np.minimum(areas[m], repaired[m])
        )
      skel.extra_attributes["cross_sectional_area"] = areas

  def prepare_labels(self, vol: "Volume"):
    """Download + mask/dust/fill — everything before the EDT. Returns
    (labels, cutout, core, bounds, local_dust) or None for empty cores.
    The batched forge runs this per task, then dispatches all K tasks'
    EDTs as one device program and injects them into execute()."""
    bounds = vol.meta.bounds(self.mip)
    core = Bbox.intersection(Bbox(self.offset, self.offset + self.shape), bounds)
    if core.empty():
      return None
    # +1 overlap: adjacent tasks share their boundary plane
    # (reference tasks/skeleton.py:68-69)
    cutout = Bbox.intersection(Bbox(core.minpt, core.maxpt + 1), bounds)
    if vol.graphene is not None and self.root_ids_cloudpath:
      # a materialized root-id layer replaces per-supervoxel graphene
      # lookups (reference tasks/skeleton.py root_ids_cloudpath use)
      roots_vol = Volume(
        self.root_ids_cloudpath, mip=self.mip,
        fill_missing=self.fill_missing, bounded=False,
      )
      labels = roots_vol.download(cutout)[..., 0]
    elif vol.graphene is not None:
      # proofreading volume: skeletonize the agglomerated root objects as
      # of the pinned timestamp (reference tasks/skeleton.py:159-164).
      # One raw download serves both the root mapping here and the
      # autapse voxel graph in execute() — stashing the supervoxels
      # avoids fetching the identical cutout twice.
      sv = vol.download(cutout)[..., 0]
      labels = vol.graphene.get_roots(sv, self.timestamp)
      self._graphene_sv = sv
    else:
      labels = vol.download(cutout)[..., 0]

    if self.object_ids:
      labels = fastremap.mask_except(labels, self.object_ids)
    if self.mask_ids:
      labels = fastremap.mask(labels, self.mask_ids)
    local_dust = self.dust_threshold
    if self.dust_global and self.dust_threshold:
      # dust by GLOBAL per-label voxel counts (CountVoxelsTask census) so
      # objects straddling task boundaries aren't wrongly dusted by their
      # per-cutout fraction (reference tasks/skeleton.py:722-755)
      labels = self._apply_global_dust(labels)
      local_dust = 0
    if self.fill_holes:
      # cavities distort the EDT and spawn spurious loops
      # (reference tasks/skeleton.py:268-301)
      from ..ops.morphology import fill_holes as _fill_holes

      labels = _fill_holes(labels, level=self.fill_holes)
    return labels, cutout, core, bounds, local_dust

  def execute(self, _prepared=None, _edt_field=None):
    vol = Volume(
      self.cloudpath, mip=self.mip, fill_missing=self.fill_missing,
      bounded=False,
    )
    prepared = _prepared if _prepared is not None else self.prepare_labels(vol)
    if prepared is None:
      return
    labels, cutout, core, bounds, local_dust = prepared
    # drop the tuple references so `del labels` in the low-memory CSA
    # path can actually free the raw cutout
    prepared = _prepared = None

    targets = (
      border_targets(
        labels,
        tuple(int(v) for v in core.size3()),
        low_sides=tuple(
          bool(core.minpt[a] > bounds.minpt[a]) for a in range(3)
        ),
      )
      if self.fix_borders
      else {}
    )
    # synapse/marker targets: global voxel coords → cutout-local
    for label, pts in self.extra_targets.items():
      arr = np.asarray(pts, dtype=np.int64).reshape(-1, 4)
      local = arr[:, :3] - np.asarray(cutout.minpt)
      inside = np.all(
        (local >= 0) & (local < np.asarray(labels.shape)), axis=1
      )
      if inside.any():
        prior = targets.get(label)
        merged = local[inside] if prior is None else np.concatenate(
          [prior, local[inside]]
        )
        targets[label] = merged
    targets = targets or None
    voxel_graph = None
    if self.fix_autapses and vol.graphene is None:
      raise ValueError("fix_autapses requires a graphene:// volume")
    if self.fix_autapses and vol.graphene is not None:
      # autapse fix (reference tasks/skeleton.py:337-398): constrain
      # TEASAR moves to the chunk graph — two supervoxels that touch
      # geometrically but share no active edge (a self-contact, or a
      # proofread split) are severed even inside one root object
      sv = getattr(self, "_graphene_sv", None)
      if sv is None:  # prepare ran in another process (batched replay)
        sv = vol.download(cutout)[..., 0]
      else:
        self._graphene_sv = None
      voxel_graph = vol.graphene.voxel_connectivity_graph(
        sv, 26, self.timestamp,
        # chunk-grid placement for clients that shade graph-chunk
        # boundaries (graphene_http.PCGClient): global cutout offset at
        # this mip + the mip->base scale
        offset=tuple(int(v) for v in cutout.minpt),
        downsample_ratio=tuple(
          int(v) for v in vol.meta.downsample_ratio(self.mip)
        ),
      )
      del sv

    skels = skeletonize(
      labels,
      anisotropy=tuple(float(v) for v in vol.resolution),
      params=TeasarParams.from_dict(self.teasar_params),
      offset=tuple(float(v) for v in cutout.minpt),
      dust_threshold=local_dust,
      extra_targets_per_label=targets,
      parallel=self.parallel,
      edt_field=_edt_field,
      voxel_graph=voxel_graph,
      fix_branching=self.fix_branching,
      fix_avocados=self.fix_avocados,
    )

    # type the synapse vertices for SWC export (reference swc_label)
    if self.extra_targets:
      res_f = np.asarray(vol.resolution, dtype=np.float32)
      for label, pts in self.extra_targets.items():
        skel = skels.get(int(label))
        if skel is None or skel.empty:
          continue
        for x, y, z, swc_label in pts:
          if not swc_label:
            continue
          phys = np.asarray([x, y, z], np.float32) * res_f
          d = np.abs(skel.vertices - phys).max(axis=1)
          hit = np.flatnonzero(d < 1e-3)
          if len(hit):
            skel.vertex_types[hit[0]] = np.uint8(swc_label)

    if self.cross_sectional_area:
      # per-vertex slice areas (xs3d capability, reference
      # tasks/skeleton.py:400-572); crop each label to its bbox so the
      # pass costs O(sum of label extents), not O(labels x volume)
      from ..ops.cross_section import cross_sectional_area as _csa

      anis = tuple(float(v) for v in vol.resolution)
      if self.low_memory_csa:
        # memory-stretch path (reference tasks/skeleton.py:477-527):
        # cseg-compress the cutout, release the raw array, and decode
        # each label's +1-shell mask lazily — peak RAM during the loop
        # is compressed payload + one label bbox
        from ..compressed import CompressedLabels

        comp = CompressedLabels(labels)
        del labels
        for label, skel in skels.items():
          got = comp.mask(int(label), margin=1)
          if got is None:
            continue
          mask, lo = got
          areas = _csa(
            mask, skel, anisotropy=anis,
            offset=tuple(
              np.asarray(cutout.minpt, np.float32)
              + np.asarray(lo, np.float32)
            ),
            smoothing_window=self.csa_smoothing_window,
          )
          skel.extra_attributes["cross_sectional_area"] = areas
        del comp  # repair re-downloads its own context regions
      else:
        by_orig = fastremap.label_bboxes(labels)
        for label, skel in skels.items():
          sl = by_orig.get(int(label))
          if sl is None:
            continue
          # +1 shell (clamped): an object ending inside the cutout keeps
          # a background border, so only genuine cutout contacts flag as
          # clipped (negative area)
          grow = tuple(
            slice(max(s.start - 1, 0), min(s.stop + 1, labels.shape[a]))
            for a, s in enumerate(sl)
          )
          crop_off = np.asarray([g.start for g in grow], dtype=np.float32)
          areas = _csa(
            labels[grow] == label, skel, anisotropy=anis,
            offset=tuple(np.asarray(cutout.minpt, np.float32) + crop_off),
            smoothing_window=self.csa_smoothing_window,
          )
          skel.extra_attributes["cross_sectional_area"] = areas
      if self.csa_repair_sec_per_label != 0:
        self._repair_csa_contacts(vol, skels, bounds)

    sdir = skel_dir_for(vol, self.skel_dir)
    cf = CloudFiles(self.frag_path or vol.cloudpath)
    res = np.asarray(vol.resolution, dtype=np.int64)
    # .frags and .spatial share the physical bbox name so merge tasks map
    # spatial-index cells to their fragment containers by rename alone
    physical = Bbox(core.minpt * res, core.maxpt * res)

    # intermediate artifacts (merge tasks consume + delete them): the
    # IGNEOUS_SCRATCH_COMPRESS knob trades scratch bytes for encode time
    # fleet-wide; unset keeps historical bytes exactly
    from ..storage import scratch_compression

    if self.sharded:
      cf.put(
        f"{sdir}/{physical.to_filename()}.frags",
        FragMap.tobytes(
          {label: s.to_precomputed() for label, s in skels.items()}
        ),
        compress=scratch_compression(None),
      )
    else:
      for label, s in skels.items():
        cf.put(f"{sdir}/{label}:{core.to_filename()}.sk", s.to_precomputed(),
               compress=scratch_compression("gzip"))

    if self.spatial_index:
      label_bounds = {}
      for label, s in skels.items():
        mn = s.vertices.min(axis=0)
        mx = s.vertices.max(axis=0) + 1
        label_bounds[label] = Bbox(mn.astype(np.int64), mx.astype(np.int64))
      SpatialIndex(cf, sdir).put(physical, label_bounds)


def _merge_label(
  fragments: List[Skeleton],
  dust_threshold: float,
  tick_threshold: float,
  max_cable_length: "float | None" = None,
) -> Skeleton:
  merged = Skeleton.simple_merge(fragments)
  if (
    max_cable_length is not None
    and merged.cable_length() > max_cable_length
  ):
    # reference :843,:999-1006: over-limit skeletons (merge-error monsters
    # fusing many cells) SKIP the expensive postprocess but are still
    # uploaded — the limit bounds compute, it does not filter output
    return merged.consolidate()
  return postprocess(
    merged, dust_threshold=dust_threshold, tick_threshold=tick_threshold
  )


class UnshardedSkeletonMergeTask(RegisteredTask):
  """Stage 2: fuse one label-prefix's fragments into final skeletons
  (reference :810-916)."""

  def __init__(
    self,
    cloudpath: str,
    prefix: str,
    skel_dir: Optional[str] = None,
    dust_threshold: float = 4000.0,
    tick_threshold: float = 6000.0,
    delete_fragments: bool = False,
    max_cable_length: Optional[float] = None,
    crop: int = 0,
  ):
    self.cloudpath = cloudpath
    self.prefix = str(prefix)
    self.skel_dir = skel_dir
    self.dust_threshold = dust_threshold
    self.tick_threshold = tick_threshold
    self.delete_fragments = delete_fragments
    self.max_cable_length = (
      float(max_cable_length) if max_cable_length is not None else None
    )
    # trim this many voxels from each fragment's bbox faces before the
    # merge (reference crop kwarg, tasks/skeleton.py:823,891-907; default
    # 0 — this build's border-pinned fragments need no trimming)
    self.crop = int(crop)

  def execute(self):
    vol = Volume(self.cloudpath)
    sdir = skel_dir_for(vol, self.skel_dir)
    cf = CloudFiles(vol.cloudpath)
    skel_info = cf.get_json(f"{sdir}/info") or {}
    attrs = skel_info.get("vertex_attributes")
    # fragment bboxes are voxel coords at the SKELETONIZATION mip (the
    # info records it); vertices are physical nm
    skel_mip = int(skel_info.get("mip", 0))

    frags = defaultdict(list)
    frag_keys = []
    for key in cf.list(f"{sdir}/{self.prefix}"):
      name = key.split("/")[-1]
      if not name.endswith(".sk"):
        continue
      label = int(name.split(":")[0])
      frag_keys.append(key)
      frags[label].append(key)

    res = np.asarray(vol.meta.resolution(skel_mip), dtype=np.float32)
    for label, keys in frags.items():
      skels = []
      for k in keys:
        skel = Skeleton.from_precomputed(cf.get(k), vertex_attributes=attrs)
        if self.crop > 0:
          # fragment filenames carry the task bbox: label:bbox.sk
          bbx = Bbox.from_filename(k.split(":", 1)[1][: -len(".sk")])
          lo = (np.asarray(bbx.minpt) + self.crop) * res
          hi = (np.asarray(bbx.maxpt) - self.crop) * res
          if np.any(hi <= lo):
            # crop would swallow the whole fragment (thin remainder at a
            # volume edge): keep it uncropped, like the reference's
            # bbx.volume() <= 0 guard (tasks/skeleton.py:911-912)
            skels.append(skel)
            continue
          keep = np.all(
            (skel.vertices >= lo - 1e-3) & (skel.vertices <= hi + 1e-3),
            axis=1,
          )
          skel = skel._select_vertices(keep)
        skels.append(skel)
      merged = _merge_label(
        skels, self.dust_threshold, self.tick_threshold,
        self.max_cable_length,
      )
      if merged.empty:
        continue
      cf.put(f"{sdir}/{label}", merged.to_precomputed(), compress="gzip")
    if self.delete_fragments:
      cf.delete(frag_keys)


class ShardedSkeletonMergeTask(RegisteredTask):
  """Stage 2 (sharded): fuse every label assigned to one shard file and
  synthesize it (reference :918-1072)."""

  def __init__(
    self,
    cloudpath: str,
    shard_no: int,
    skel_dir: Optional[str] = None,
    dust_threshold: float = 4000.0,
    tick_threshold: float = 6000.0,
    max_cable_length: Optional[float] = None,
  ):
    self.cloudpath = cloudpath
    self.shard_no = int(shard_no)
    self.skel_dir = skel_dir
    self.dust_threshold = dust_threshold
    self.tick_threshold = tick_threshold
    self.max_cable_length = (
      float(max_cable_length) if max_cable_length is not None else None
    )

  def execute(self):
    from ..sharding import ShardingSpecification

    vol = Volume(self.cloudpath)
    sdir = skel_dir_for(vol, self.skel_dir)
    cf = CloudFiles(vol.cloudpath)
    skel_info = cf.get_json(f"{sdir}/info") or {}
    spec = ShardingSpecification.from_dict(skel_info["sharding"])

    # labels for this shard: spatial-index census filtered by shard number
    si = SpatialIndex(cf, sdir)
    locations = si.file_locations_per_label()
    labels = np.array(sorted(locations.keys()), dtype=np.uint64)
    if len(labels) == 0:
      return
    mine = labels[spec.shard_number(labels) == self.shard_no]
    if len(mine) == 0:
      return

    # fetch fragments: .spatial cell file ↔ .frags container (same bbox)
    needed_files = sorted({
      f for lbl in mine for f in locations[int(lbl)]
    })
    # fetch containers concurrently (reference fetches fragments via a
    # ThreadPoolExecutor, multires.py:459); order preserved for
    # deterministic merge input ordering
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=8) as ex:
      datas = list(ex.map(
        lambda k: cf.get(k.replace(".spatial", ".frags")), needed_files
      ))
    fragmaps = [FragMap.frombytes(d) for d in datas if d is not None]

    attrs = skel_info.get("vertex_attributes")
    out = {}
    for label in mine.tolist():
      pieces = []
      for fm in fragmaps:
        blob = fm.get(label)
        if blob is not None:
          pieces.append(Skeleton.from_precomputed(blob, vertex_attributes=attrs))
      if not pieces:
        continue
      merged = _merge_label(
        pieces, self.dust_threshold, self.tick_threshold,
        self.max_cable_length,
      )
      if not merged.empty:
        out[int(label)] = merged.to_precomputed()

    if out:
      files = spec.synthesize_shard_files(out)
      for filename, data in files.items():
        cf.put(f"{sdir}/{filename}", data, compress=None)


class ShardedFromUnshardedSkeletonMergeTask(RegisteredTask):
  """Re-pack finished unsharded skeletons into one shard file
  (reference :1091-1130)."""

  def __init__(
    self,
    cloudpath: str,
    shard_no: int,
    src_skel_dir: str,
    skel_dir: str,
    dest_cloudpath: "str | None" = None,
  ):
    self.cloudpath = cloudpath
    self.shard_no = int(shard_no)
    self.src_skel_dir = src_skel_dir
    self.skel_dir = skel_dir
    # write the shard into a different volume (`skeleton xfer --sharded`)
    self.dest_cloudpath = dest_cloudpath

  def execute(self):
    from ..sharding import ShardingSpecification

    vol = Volume(self.cloudpath)
    cf = CloudFiles(vol.cloudpath)
    out_cf = CloudFiles(self.dest_cloudpath or self.cloudpath)
    skel_info = out_cf.get_json(f"{self.skel_dir}/info") or {}
    spec = ShardingSpecification.from_dict(skel_info["sharding"])

    labels = []
    for key in cf.list(f"{self.src_skel_dir}/"):
      name = key.split("/")[-1]
      if name.isdigit():  # finished skeletons are bare label files
        labels.append(int(name))
    labels = np.array(sorted(labels), dtype=np.uint64)
    if len(labels) == 0:
      return
    mine = labels[spec.shard_number(labels) == self.shard_no]

    out = {}
    for label in mine.tolist():
      data = cf.get(f"{self.src_skel_dir}/{label}")
      if data is not None:
        out[int(label)] = data
    if out:
      files = spec.synthesize_shard_files(out)
      for filename, data in files.items():
        out_cf.put(f"{self.skel_dir}/{filename}", data, compress=None)


@queueable
def TransferSkeletonFilesTask(
  src: str, dest: str, skel_dir: str, prefix: str = ""
):
  cf = CloudFiles(src)
  cf.transfer_to(dest, paths=list(cf.list(f"{skel_dir}/{prefix}")))


@queueable
def DeleteSkeletonFilesTask(cloudpath: str, skel_dir: str, prefix: str = ""):
  cf = CloudFiles(cloudpath)
  cf.delete(list(cf.list(f"{skel_dir}/{prefix}")))
