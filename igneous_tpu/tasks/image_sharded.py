"""Sharded image tasks.

Reference parity: ImageShardTransferTask
(/root/reference/igneous/tasks/image/image.py:596-679) and
ImageShardDownsampleTask (:681-847). One task produces complete shard
file(s): shard files are immutable, so the task grid is shard-aligned.

TPU-first difference: the reference's z-stripe renumber loop exists to fit
64-bit labels in RAM; here the cutout goes to the device whole (uint64 as
hi/lo planes) and one program emits the downsampled shard content.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..lib import Bbox, Vec
from ..pipeline import SerialSink, StagePlan
from ..queues.registry import RegisteredTask
from ..volume import Volume
from ..ops import pooling
from ..sharded_image import upload_shard

# shard-aligned empty cutouts stage as no-ops (see tasks/image.py)
_NOOP_PLAN = StagePlan(lambda: None, lambda p: None, lambda o, s: None)


class ImageShardTransferTask(RegisteredTask):
  """Copy a shard-aligned cutout into a sharded destination scale."""

  def __init__(
    self,
    src_path: str,
    dest_path: str,
    shape: Sequence[int],
    offset: Sequence[int],
    mip: int = 0,
    fill_missing: bool = False,
    translate: Sequence[int] = (0, 0, 0),
    agglomerate: bool = False,
    timestamp=None,
    stop_layer=None,
  ):
    self.src_path = src_path
    self.dest_path = dest_path
    self.shape = Vec(*shape)
    self.offset = Vec(*offset)
    self.mip = int(mip)
    self.fill_missing = fill_missing
    self.translate = Vec(*translate)
    # graphene sources: materialize proofread root (or L2) ids while
    # copying, mirroring TransferTask's surface
    self.agglomerate = bool(agglomerate)
    self.timestamp = timestamp
    self.stop_layer = stop_layer

  def trace_attrs(self) -> dict:
    return {
      "dest": self.dest_path,
      "mip": self.mip,
      "bbox": f"{tuple(self.offset)}+{tuple(self.shape)}",
    }

  def execute(self):
    plan = self.stage_plan()
    plan.upload(plan.compute(plan.download()), SerialSink())

  def stage_plan(self):
    """Pipeline decomposition: shard synthesis is one indivisible write
    (shard files are immutable), so the whole upload_shard call rides
    the sink as a single unit — it overlaps the NEXT task's download
    and compute rather than parallelizing internally."""
    src = Volume(self.src_path, mip=self.mip, fill_missing=self.fill_missing)
    dest = Volume(self.dest_path, mip=self.mip)
    bounds = Bbox.intersection(
      Bbox(self.offset, self.offset + self.shape), src.bounds
    )
    if bounds.empty():
      return _NOOP_PLAN

    def download():
      return src.download(
        bounds, agglomerate=self.agglomerate, timestamp=self.timestamp,
        stop_layer=self.stop_layer,
      )

    def upload(img, sink):
      sink.submit(lambda: upload_shard(
        dest, bounds.translate(self.translate), img, self.mip
      ))

    nbytes = int(np.prod([int(v) for v in bounds.size3()]))
    nbytes *= dest.dtype.itemsize * dest.num_channels
    return StagePlan(
      download, lambda img: img, upload,
      reads={(self.src_path, self.mip)},
      writes={(self.dest_path, self.mip)},
      nbytes_hint=nbytes,
      # shard files are immutable and each is written exactly once by
      # the task owning its shard-aligned bbox: no read-modify-write, so
      # same-(path, mip) shard writers may overlap in the pipeline
      aligned_writes=True,
    )


class ImageShardDownsampleTask(RegisteredTask):
  """Downsample a shard-aligned region of mip into sharded mip+1…mip+N.

  The task bbox (shape/offset, in source-mip coords) covers whole
  destination shards at every produced mip (or their dataset-edge
  remainders); the factory's stride math guarantees that
  (reference image.py:681-847 multi-mip shard synthesis)."""

  def __init__(
    self,
    src_path: str,
    shape: Sequence[int],
    offset: Sequence[int],
    mip: int = 0,
    fill_missing: bool = False,
    sparse: bool = False,
    factor: Sequence[int] = (2, 2, 1),
    downsample_method: str = "auto",
    num_mips: int = 1,
    agglomerate: bool = False,
    timestamp=None,
  ):
    self.src_path = src_path
    self.shape = Vec(*shape)
    self.offset = Vec(*offset)
    self.mip = int(mip)
    self.fill_missing = fill_missing
    self.sparse = sparse
    self.factor = Vec(*factor)
    self.downsample_method = downsample_method
    self.num_mips = int(num_mips)
    self.agglomerate = bool(agglomerate)
    self.timestamp = timestamp

  def trace_attrs(self) -> dict:
    return {
      "dest": self.src_path,  # sharded downsample writes back to src layer
      "mip": self.mip,
      "bbox": f"{tuple(self.offset)}+{tuple(self.shape)}",
    }

  def execute(self):
    plan = self.stage_plan()
    plan.upload(plan.compute(plan.download()), SerialSink())

  def stage_plan(self):
    vol = Volume(self.src_path, mip=self.mip, fill_missing=self.fill_missing)
    bounds = Bbox.intersection(
      Bbox(self.offset, self.offset + self.shape), vol.bounds
    )
    if bounds.empty():
      return _NOOP_PLAN
    factor = tuple(int(v) for v in self.factor)
    cum = np.ones(3, dtype=np.int64)
    dest_mips = []
    for _ in range(self.num_mips):
      cum *= np.asarray(factor, dtype=np.int64)
      # resolve each destination scale by resolution, not positional
      # index: add_scale keeps scales sorted, so mip+i is not guaranteed
      dest_res = np.asarray(vol.meta.resolution(self.mip)) * cum
      dest_mips.append((vol.meta.mip_from_resolution(dest_res), cum.copy()))

    def download():
      return vol.download(
        bounds, agglomerate=self.agglomerate, timestamp=self.timestamp
      )

    def compute(img):
      method = pooling.method_for_layer(vol.layer_type, self.downsample_method)
      return pooling.downsample_auto(
        img, factor, self.num_mips, method=method, sparse=self.sparse,
      )

    def upload(mips_out, sink):
      for mipped, (dest_mip, cumf) in zip(mips_out, dest_mips):
        dest_min = bounds.minpt // Vec(*cumf)
        dest_bounds = Bbox(dest_min, dest_min + Vec(*mipped.shape[:3]))
        dest_bounds = Bbox.intersection(dest_bounds, vol.meta.bounds(dest_mip))
        sl = tuple(slice(0, int(s)) for s in dest_bounds.size3())
        sink.submit(
          lambda m=mipped, b=dest_bounds, s=sl, d=dest_mip:
            upload_shard(vol, b, m[s], d)
        )

    nbytes = int(np.prod([int(v) for v in bounds.size3()]))
    nbytes *= vol.dtype.itemsize * vol.num_channels
    return StagePlan(
      download, compute, upload,
      reads={(self.src_path, self.mip)},
      writes={(self.src_path, m) for m, _ in dest_mips},
      nbytes_hint=nbytes,
      # immutable one-shot shard writes (see ImageShardTransferTask)
      aligned_writes=True,
    )
