"""Sharded image tasks.

Reference parity: ImageShardTransferTask
(/root/reference/igneous/tasks/image/image.py:596-679) and
ImageShardDownsampleTask (:681-847). One task produces complete shard
file(s): shard files are immutable, so the task grid is shard-aligned.

TPU-first difference: the reference's z-stripe renumber loop exists to fit
64-bit labels in RAM; here the cutout goes to the device whole (uint64 as
hi/lo planes) and one program emits the downsampled shard content.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..lib import Bbox, Vec
from ..queues.registry import RegisteredTask
from ..volume import Volume
from ..ops import pooling
from ..sharded_image import upload_shard


class ImageShardTransferTask(RegisteredTask):
  """Copy a shard-aligned cutout into a sharded destination scale."""

  def __init__(
    self,
    src_path: str,
    dest_path: str,
    shape: Sequence[int],
    offset: Sequence[int],
    mip: int = 0,
    fill_missing: bool = False,
    translate: Sequence[int] = (0, 0, 0),
  ):
    self.src_path = src_path
    self.dest_path = dest_path
    self.shape = Vec(*shape)
    self.offset = Vec(*offset)
    self.mip = int(mip)
    self.fill_missing = fill_missing
    self.translate = Vec(*translate)

  def execute(self):
    src = Volume(self.src_path, mip=self.mip, fill_missing=self.fill_missing)
    dest = Volume(self.dest_path, mip=self.mip)
    bounds = Bbox.intersection(
      Bbox(self.offset, self.offset + self.shape), src.bounds
    )
    if bounds.empty():
      return
    img = src.download(bounds)
    upload_shard(dest, bounds.translate(self.translate), img, self.mip)


class ImageShardDownsampleTask(RegisteredTask):
  """Downsample a shard-aligned region of mip into sharded mip+1.

  The task bbox (shape/offset, in source-mip coords) covers exactly one
  destination shard (or its dataset-edge remainder)."""

  def __init__(
    self,
    src_path: str,
    shape: Sequence[int],
    offset: Sequence[int],
    mip: int = 0,
    fill_missing: bool = False,
    sparse: bool = False,
    factor: Sequence[int] = (2, 2, 1),
    downsample_method: str = "auto",
  ):
    self.src_path = src_path
    self.shape = Vec(*shape)
    self.offset = Vec(*offset)
    self.mip = int(mip)
    self.fill_missing = fill_missing
    self.sparse = sparse
    self.factor = Vec(*factor)
    self.downsample_method = downsample_method

  def execute(self):
    vol = Volume(self.src_path, mip=self.mip, fill_missing=self.fill_missing)
    bounds = Bbox.intersection(
      Bbox(self.offset, self.offset + self.shape), vol.bounds
    )
    if bounds.empty():
      return
    img = vol.download(bounds)
    method = pooling.method_for_layer(vol.layer_type, self.downsample_method)
    mipped = pooling.downsample_auto(
      img, tuple(int(v) for v in self.factor), 1, method=method,
      sparse=self.sparse,
    )[0]
    # resolve the destination scale by resolution, not positional index:
    # add_scale keeps scales sorted, so mip+1 is not guaranteed to be ours
    dest_res = np.asarray(vol.meta.resolution(self.mip)) * np.asarray(
      [int(v) for v in self.factor]
    )
    dest_mip = vol.meta.mip_from_resolution(dest_res)
    dest_min = bounds.minpt // self.factor
    dest_bounds = Bbox(dest_min, dest_min + Vec(*mipped.shape[:3]))
    dest_bounds = Bbox.intersection(dest_bounds, vol.meta.bounds(dest_mip))
    sl = tuple(slice(0, int(s)) for s in dest_bounds.size3())
    upload_shard(vol, dest_bounds, mipped[sl], dest_mip)
