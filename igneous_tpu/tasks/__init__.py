"""Task definitions: serializable download→compute→upload work units.

This package is the task registry: importing it registers every task class
(the reference's equivalent is /root/reference/igneous/tasks/__init__.py).
Worker processes import this module before deserializing payloads.
"""

from ..queues.registry import PrintTask, RegisteredTask
from .audit import IntegrityAuditTask
from .image import (
  BlackoutTask,
  DeleteTask,
  DownsampleTask,
  QuantizeTask,
  TouchTask,
  TransferTask,
  downsample_and_upload,
)
from .image_sharded import ImageShardDownsampleTask, ImageShardTransferTask
from .ccl import CCLEquivalancesTask, CCLFacesTask, RelabelCCLTask
from .mesh import (
  DeleteMeshFilesTask,
  GrapheneMeshTask,
  MeshManifestFilesystemTask,
  MeshManifestPrefixTask,
  MeshTask,
  TransferMeshFilesTask,
)
from .skeleton import (
  DeleteSkeletonFilesTask,
  ShardedFromUnshardedSkeletonMergeTask,
  ShardedSkeletonMergeTask,
  SkeletonTask,
  TransferSkeletonFilesTask,
  UnshardedSkeletonMergeTask,
)
from .mesh_multires import (
  MultiResShardedFromUnshardedMeshMergeTask,
  MultiResShardedMeshMergeTask,
  MultiResUnshardedMeshMergeTask,
)
from .contrast import CLAHETask, ContrastNormalizationTask, LuminanceLevelsTask
from .inference import InferenceTask
from .obsolete import (
  HyperSquareConsensusTask,
  LegacyInferenceTask,
  MaskAffinitymapTask,
  WatershedRemapTask,
  register_inference_model,
)
from .stats import (
  CountVoxelsTask,
  ReorderTask,
  SpatialIndexTask,
  accumulate_voxel_counts,
  load_voxel_counts,
)


class TouchFileTask(RegisteredTask):
  """Creates an empty file; used for queue smoke tests and liveness probes."""

  def __init__(self, path: str):
    self.path = path

  def execute(self):
    import os

    os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
    with open(self.path, "a"):
      pass


class FailTask(RegisteredTask):
  """Always raises; exercises lease-recycling / at-least-once delivery."""

  def __init__(self, message: str = "intentional failure"):
    self.message = message

  def execute(self):
    raise RuntimeError(self.message)


class SleepTask(RegisteredTask):
  """Sleeps for a fixed duration; gives smoke campaigns (and the fleet
  simulator's calibration runs) a task whose true cost is known."""

  def __init__(self, seconds: float = 0.05):
    self.seconds = seconds

  def execute(self):
    import time

    time.sleep(float(self.seconds))
