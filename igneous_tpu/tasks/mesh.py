"""Mesh forge + manifest + maintenance tasks.

Reference parity: /root/reference/igneous/tasks/mesh/mesh.py
  MeshTask (:39-464): per-cutout meshing with 1-voxel high-side overlap for
  seam-free stitching, dataset-edge closing, dust, object_ids masking,
  simplification, sharded `.frags` vs individual fragments, spatial index.
  MeshManifestPrefixTask / MeshManifestFilesystemTask (:624-724)
  TransferMeshFilesTask (:726), DeleteMeshFilesTask (:741)

TPU-first difference: isosurface extraction runs on device
(ops.mesh.marching_cubes by default; ``mesher="tetrahedra"`` selects the
6-tet variant) per label over its cropped bounding box.
"""

from __future__ import annotations

import json
from collections import defaultdict
from typing import Optional, Sequence

import numpy as np
from scipy import ndimage

from ..lib import Bbox, Vec
from ..queues.registry import RegisteredTask, queueable
from ..storage import CloudFiles
from ..volume import Volume
from ..mesh_io import FragMap, Mesh, encode_mesh, simplify
from ..ops import remap as fastremap
from ..ops.mesh import marching_cubes_batch, marching_tetrahedra_batch
from ..spatial_index import SpatialIndex


def mesh_dir_for(vol: Volume, mesh_dir: Optional[str]) -> str:
  if mesh_dir:
    return mesh_dir
  if vol.info.get("mesh"):
    return vol.info["mesh"]
  raise ValueError("No mesh directory configured in the info file")


class MeshTask(RegisteredTask):
  # labels per device dispatch group (bounds host+HBM mask memory)
  MESH_BATCH = 16

  def __init__(
    self,
    shape: Sequence[int],
    offset: Sequence[int],
    layer_path: str,
    mip: int = 0,
    simplification_factor: int = 100,
    max_simplification_error: int = 40,
    mesh_dir: Optional[str] = None,
    dust_threshold: Optional[int] = None,
    dust_global: bool = False,
    object_ids: Optional[Sequence[int]] = None,
    exclude_object_ids: Optional[Sequence[int]] = None,
    remap_table: Optional[dict] = None,
    fill_missing: bool = False,
    encoding: str = "precomputed",
    spatial_index: bool = True,
    sharded: bool = False,
    closed_dataset_edges: bool = True,
    fill_holes: int = 0,
    timestamp: Optional[float] = None,
    mesher: str = "cubes",
    parallel: int = 1,
    compress: str = "gzip",
  ):
    self.shape = Vec(*shape)
    self.offset = Vec(*offset)
    self.layer_path = layer_path
    self.mip = int(mip)
    self.simplification_factor = simplification_factor
    self.max_simplification_error = max_simplification_error
    self.mesh_dir = mesh_dir
    self.dust_threshold = dust_threshold
    self.dust_global = bool(dust_global)
    self.object_ids = list(object_ids) if object_ids else None
    self.exclude_object_ids = (
      list(exclude_object_ids) if exclude_object_ids else None
    )
    # {orig_id: new_id} agglomeration applied before meshing (reference
    # mesh.py remap_table: proofreading merges without rewriting the
    # stored segmentation). Only the table's keys are meshed; see execute.
    self.remap_table = (
      {int(k): int(v) for k, v in remap_table.items()} if remap_table
      else None
    )
    self.fill_missing = fill_missing
    self.encoding = encoding
    self.spatial_index = spatial_index
    self.sharded = sharded
    self.closed_dataset_edges = closed_dataset_edges
    self.fill_holes = int(fill_holes)
    self.timestamp = timestamp
    if mesher not in ("cubes", "tetrahedra"):
      raise ValueError(f"mesher must be 'cubes' or 'tetrahedra': {mesher!r}")
    self.mesher = mesher
    self.compress = compress or None
    # label-level threading for the simplification stage, mirroring
    # SkeletonTask's parallel= (the native QEM collapse is a ctypes call
    # that releases the GIL; results are per-label independent and
    # deterministic regardless of completion order)
    self.parallel = int(parallel)

  def execute(self):
    ctx = self.prepare_jobs()
    if ctx is None:
      return
    mesher_batch = (
      marching_cubes_batch if self.mesher == "cubes"
      else marching_tetrahedra_batch
    )
    for g0 in range(0, len(ctx["jobs"]), self.MESH_BATCH):
      group = ctx["jobs"][g0 : g0 + self.MESH_BATCH]
      results = mesher_batch(
        self.group_masks(ctx, group),
        anisotropy=ctx["resolution"],
        offsets=self.group_offsets(ctx, group),
      )
      self.finish_group(ctx, group, results)
    self.finalize(ctx)

  def prepare_jobs(self):
    """Download + label prep + job planning — everything before the
    device count pass. Returns a context dict (or None when there is
    nothing to mesh) so the lease batcher can merge many tasks' label
    masks into shared count-pass dispatches (parallel/lease_batcher.py);
    execute() drives the same stages solo."""
    vol = Volume(
      self.layer_path, mip=self.mip, fill_missing=self.fill_missing,
      bounded=False,
    )
    bounds = vol.meta.bounds(self.mip)
    core = Bbox.intersection(Bbox(self.offset, self.offset + self.shape), bounds)
    if core.empty():
      return None
    # 1-voxel high-side overlap: adjacent tasks share a boundary plane so
    # their surfaces meet exactly (reference mesh.py:64-69,155-160)
    cutout = Bbox.intersection(Bbox(core.minpt, core.maxpt + 1), bounds)
    if vol.graphene is not None:
      # graphene volumes mesh at L2 granularity (reference
      # GrapheneMeshTask, mesh.py:466-622): stable chunk-local ids whose
      # meshes the proofreading frontend stitches per root
      img = vol.download(
        cutout, stop_layer=2, timestamp=self.timestamp
      )[..., 0]
    else:
      img = vol.download(cutout)[..., 0]

    if self.remap_table:
      # reference semantics (mesh.py:358-369): ONLY the table's keys are
      # meshed — everything else is masked to background first — and
      # background can never be remapped into a real label
      table = dict(self.remap_table)
      table[0] = 0
      img = fastremap.mask_except(img, list(table.keys()))
      img = fastremap.remap(img, table)

    if self.object_ids:
      img = fastremap.mask_except(img, self.object_ids)

    if self.exclude_object_ids:
      img = fastremap.mask(img, self.exclude_object_ids)

    if self.fill_holes:
      # close internal cavities so meshes have no interior shells
      # (reference mesh.py:211-246 fastmorph.fill_holes levels; see
      # ops.morphology.fill_holes for the level ladder)
      from ..ops.morphology import fill_holes as _fill_holes

      img = _fill_holes(img, level=self.fill_holes)

    # zero-pad where the cutout touches the dataset boundary so surfaces
    # close instead of gaping (reference mesh.py:267-303); interior task
    # edges stay open — the neighbor task supplies the adjoining surface
    pad_lo = [int(cutout.minpt[a] == bounds.minpt[a]) for a in range(3)]
    pad_hi = [int(cutout.maxpt[a] == bounds.maxpt[a]) for a in range(3)]
    if not self.closed_dataset_edges:
      pad_lo = [0, 0, 0]
      pad_hi = [0, 0, 0]
    img = np.pad(
      img, tuple(zip(pad_lo, pad_hi)), mode="constant", constant_values=0
    )
    origin = cutout.minpt - Vec(*pad_lo)

    labels, counts = np.unique(img, return_counts=True)
    sel = labels != 0
    if self.dust_threshold and self.dust_global:
      # dust by GLOBAL voxel counts so objects straddling task borders
      # are not wrongly dusted (reference mesh.py:313-355 dust_global)
      from .stats import globally_small_labels

      small = set(globally_small_labels(
        self.layer_path, self.mip, labels[sel], self.dust_threshold,
      ))
      sel &= np.asarray([int(l) not in small for l in labels])
    elif self.dust_threshold:
      sel &= counts >= self.dust_threshold
    labels = labels[sel]
    if len(labels) == 0:
      self._upload({}, core, cutout, vol)
      return None

    # crop each label to its bounding box (find_objects) before meshing
    dense, mapping = fastremap.renumber(img)
    slices = ndimage.find_objects(dense.astype(np.int32))
    resolution = np.asarray(vol.resolution, dtype=np.float32)

    # labels are this task's batch dimension: every label's count pass
    # runs as one shard_map'd device dispatch per shape bucket instead of
    # one dispatch per label (VERDICT round-1 item 3). Masks materialize
    # per group of MESH_BATCH labels, never all at once.
    jobs = []
    keep = set(int(l) for l in labels)
    for new_id, sl in enumerate(slices, start=1):
      orig = mapping[new_id]
      if sl is None or orig not in keep:
        continue
      grow = tuple(
        slice(max(s.start - 1, 0), min(s.stop + 1, img.shape[a]))
        for a, s in enumerate(sl)
      )
      jobs.append((int(orig), grow, int(new_id)))

    return {
      "vol": vol, "core": core, "cutout": cutout, "origin": origin,
      "dense": dense, "jobs": jobs, "resolution": resolution,
      "res_int": np.asarray(vol.resolution, dtype=np.int64),
      "meshes": {}, "label_bounds": {},
    }

  @staticmethod
  def group_masks(ctx, group):
    return [ctx["dense"][grow] == new_id for _, grow, new_id in group]

  @staticmethod
  def group_offsets(ctx, group):
    return [
      np.asarray(ctx["origin"], dtype=np.float32)
      + np.asarray([g.start for g in grow], dtype=np.float32)
      for _, grow, _ in group
    ]

  def finish_group(self, ctx, group, results):
    """Host stage for one group of labels: weld/simplify/bbox, threaded
    by self.parallel like the solo path."""
    origin, res_int = ctx["origin"], ctx["res_int"]

    def _finish(args):
      (orig, grow, _), (verts, faces) = args
      mesh = Mesh(verts, faces)
      if self.simplification_factor > 1:
        mesh = simplify(
          mesh, self.simplification_factor, self.max_simplification_error
        )
      mn = (np.asarray([g.start for g in grow]) + np.asarray(origin)) * res_int
      mx = (np.asarray([g.stop for g in grow]) + np.asarray(origin)) * res_int
      return orig, mesh, Bbox(mn, mx)

    pairs = list(zip(group, results))
    if self.parallel > 1 and len(pairs) > 1:
      from concurrent.futures import ThreadPoolExecutor

      with ThreadPoolExecutor(max_workers=self.parallel) as ex:
        finished = list(ex.map(_finish, pairs))
    else:
      finished = [_finish(p) for p in pairs]
    for orig, mesh, bbx in finished:
      ctx["meshes"][orig] = mesh
      ctx["label_bounds"][orig] = bbx

  def finalize(self, ctx):
    self._upload(
      ctx["meshes"], ctx["core"], ctx["cutout"], ctx["vol"],
      ctx["label_bounds"],
    )

  def _upload(self, meshes, core, cutout, vol, label_bounds=None):
    mdir = mesh_dir_for(vol, self.mesh_dir)
    cf = CloudFiles(vol.cloudpath)
    res = np.asarray(vol.resolution, dtype=np.int64)
    # .frags and .spatial share the physical bbox name so merge consumers
    # map spatial-index cells to fragment containers by rename alone
    physical = Bbox(core.minpt * res, core.maxpt * res)

    if self.sharded:
      # the container itself stays uncompressed so ranged reads into the
      # key table keep working (zero-parse random access); gzip would
      # force merge consumers to download whole containers
      frags = {
        label: encode_mesh(m, self.encoding) for label, m in meshes.items()
      }
      cf.put(f"{mdir}/{physical.to_filename()}.frags", FragMap.tobytes(frags))
    else:
      for label, m in meshes.items():
        cf.put(
          f"{mdir}/{label}:0:{core.to_filename()}",
          encode_mesh(m, self.encoding),
          compress=self.compress,
        )

    if self.spatial_index and label_bounds is not None:
      SpatialIndex(cf, mdir).put(physical, label_bounds)


class _CountingKernelExecutor:
  """Wraps a BatchKernelExecutor to count device dispatches (the lease
  batcher's stats surface asserts on these)."""

  def __init__(self, inner):
    self.inner = inner
    self.calls = 0

  def __call__(self, batch):
    self.calls += 1
    return self.inner(batch)


def execute_mesh_tasks_batched(tasks, batch_size=None, mesh=None):
  """Run K MeshTasks with the marching-cubes count pass batched ACROSS
  tasks: all tasks' per-label masks feed one shared dispatch stream (per
  mask-shape bucket) instead of each task filling its own partial
  batches. Host stages (weld/simplify/upload) stay per task and
  byte-identical to solo execution.

  Callers group tasks by (layer, mip, mesher) — see
  parallel/lease_batcher.py — so resolution and kernel agree across the
  stream; ``mesh`` pins dispatches to an injected device mesh. Per-task
  failures are stashed on ``task._batch_error`` (the lease batcher
  re-raises them per member so only that lease recycles); returns the
  number of device dispatches issued.
  """
  import concurrent.futures as cf

  from ..ops.mesh import _count_kernel, _mc_count_kernel, _mc_executor, _mt_executor

  bs = int(batch_size) if batch_size else MeshTask.MESH_BATCH
  for t in tasks:
    t._batch_error = None

  def prep(task):
    try:
      return task.prepare_jobs()
    except Exception as e:  # noqa: BLE001 - stashed, re-raised per lease
      task._batch_error = e
      return None

  with cf.ThreadPoolExecutor(max_workers=8) as pool:
    ctxs = list(pool.map(prep, tasks))

  stream = []
  for task, ctx in zip(tasks, ctxs):
    if ctx is None:
      continue
    for job in ctx["jobs"]:
      stream.append((task, ctx, job))

  mesher = tasks[0].mesher
  if mesh is not None:
    from ..parallel.executor import BatchKernelExecutor

    inner = BatchKernelExecutor(
      _mc_count_kernel if mesher == "cubes" else _count_kernel, mesh=mesh
    )
  else:
    inner = _mc_executor() if mesher == "cubes" else _mt_executor()
  counting = _CountingKernelExecutor(inner)
  mesher_batch = (
    marching_cubes_batch if mesher == "cubes" else marching_tetrahedra_batch
  )
  for g0 in range(0, len(stream), bs):
    grp = [e for e in stream[g0 : g0 + bs] if e[0]._batch_error is None]
    if not grp:
      continue
    masks = [t.group_masks(ctx, [job])[0] for t, ctx, job in grp]
    offsets = [t.group_offsets(ctx, [job])[0] for t, ctx, job in grp]
    results = mesher_batch(
      masks, anisotropy=grp[0][1]["resolution"], offsets=offsets,
      executor=counting, batch_size=bs,
    )
    # hand each task its own labels' results
    per_task = {}
    for (task, ctx, job), res in zip(grp, results):
      per_task.setdefault(id(task), (task, ctx, [], []))
      per_task[id(task)][2].append(job)
      per_task[id(task)][3].append(res)
    for task, ctx, jobs, ress in per_task.values():
      try:
        task.finish_group(ctx, jobs, ress)
      except Exception as e:  # noqa: BLE001
        task._batch_error = e
  dispatches = counting.calls

  def final(args):
    task, ctx = args
    if ctx is None or task._batch_error is not None:
      return
    try:
      task.finalize(ctx)
    except Exception as e:  # noqa: BLE001
      task._batch_error = e

  with cf.ThreadPoolExecutor(max_workers=8) as pool:
    list(pool.map(final, zip(tasks, ctxs)))
  return dispatches


class MeshManifestPrefixTask(RegisteredTask):
  """Stage 2 (legacy format): group fragment files by label for one label
  prefix; write ``<label>:0`` manifests (reference mesh.py:672-724)."""

  def __init__(self, layer_path: str, prefix: str, mesh_dir: Optional[str] = None):
    self.layer_path = layer_path
    self.prefix = str(prefix)
    self.mesh_dir = mesh_dir

  def execute(self):
    vol = Volume(self.layer_path)
    mdir = mesh_dir_for(vol, self.mesh_dir)
    cf = CloudFiles(vol.cloudpath)
    fragments = defaultdict(list)
    for key in cf.list(f"{mdir}/{self.prefix}"):
      name = key.split("/")[-1]
      parts = name.split(":")
      if len(parts) != 3:  # skip manifests/spatial files
        continue
      fragments[parts[0]].append(name)
    for label, frags in fragments.items():
      cf.put_json(f"{mdir}/{label}:0", {"fragments": sorted(frags)})


class MeshManifestFilesystemTask(RegisteredTask):
  """Stage 2 over the whole mesh dir in one task (small datasets)."""

  def __init__(self, layer_path: str, mesh_dir: Optional[str] = None):
    self.layer_path = layer_path
    self.mesh_dir = mesh_dir

  def execute(self):
    MeshManifestPrefixTask(
      layer_path=self.layer_path, prefix="", mesh_dir=self.mesh_dir
    ).execute()


@queueable
def TransferMeshFilesTask(
  src: str, dest: str, mesh_dir: str, prefix: str = ""
):
  cf = CloudFiles(src)
  paths = list(cf.list(f"{mesh_dir}/{prefix}"))
  cf.transfer_to(dest, paths=paths)


@queueable
def DeleteMeshFilesTask(cloudpath: str, mesh_dir: str, prefix: str = ""):
  cf = CloudFiles(cloudpath)
  cf.delete(list(cf.list(f"{mesh_dir}/{prefix}")))


class GrapheneMeshTask(MeshTask):
  """Mesh forge for graphene:// proofreading volumes — reference
  GrapheneMeshTask (/root/reference/igneous/tasks/mesh/mesh.py:466-622).

  Identical pipeline to MeshTask except the cutout downloads at L2
  granularity (stop_layer=2, stable per-(root, chunk) ids via the
  chunk-graph client) and defaults to draco-encoded sharded .frags — the
  stage-1 payload the proofreading frontend's per-root stitcher consumes.
  The 1-voxel overlap plus identical L2 ids on shared planes make
  adjacent chunk meshes weld exactly (the role of the reference's
  mesh_graphene_remap overlap relabeling).
  """

  def __init__(
    self,
    shape: Sequence[int],
    offset: Sequence[int],
    layer_path: str,
    mip: int = 0,
    simplification_factor: int = 100,
    max_simplification_error: int = 40,
    mesh_dir: Optional[str] = None,
    fill_missing: bool = False,
    encoding: str = "draco",
    timestamp: Optional[float] = None,
    object_ids: Optional[Sequence[int]] = None,
  ):
    super().__init__(
      shape=shape,
      offset=offset,
      layer_path=layer_path,
      mip=mip,
      simplification_factor=simplification_factor,
      max_simplification_error=max_simplification_error,
      mesh_dir=mesh_dir,
      fill_missing=fill_missing,
      encoding=encoding,
      sharded=True,
      timestamp=timestamp,
      object_ids=object_ids,
    )
