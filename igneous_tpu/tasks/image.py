"""Image-plane tasks: transfer, downsample, delete, blackout, touch, quantize.

Behavioral parity targets in the reference:
  TransferTask     /root/reference/igneous/tasks/image/image.py:434-517
  DownsampleTask   /root/reference/igneous/tasks/image/image.py:519-550
  downsample_and_upload pyramid builder      image.py:57-100
  DeleteTask :102  BlackoutTask :124  TouchTask :137  QuantizeTask :145

TPU-first difference: the per-task mip pyramid is produced by ONE jitted
device program (ops.pooling), not per-mip C calls; uint64 segmentation is
renumbered to ≤32-bit labels before the device pass and remapped on the
way out (the reference renumbers for memory at image.py:749-760; here it is
what keeps label compute in the TPU's native integer width).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import numpy as np

from ..lib import Bbox, Vec
from ..queues.registry import RegisteredTask, queueable
from ..volume import Volume
from ..downsample_scales import (
  DEFAULT_FACTOR,
  compute_factors,
  truncate_writable_factors,
)
from ..ops import pooling
from ..pipeline import StagePlan
from .. import telemetry

from ..analysis import knobs

# empty-cutout tasks stage as no-ops: the pipeline treats them uniformly
# instead of barriering the stream for a solo no-op execute()
_NOOP_PLAN = StagePlan(lambda: None, lambda p: None, lambda o, s: None)


def _passthrough_enabled() -> bool:
  """``IGNEOUS_TRANSFER_PASSTHROUGH=0|off`` forces eligible transfers down
  the decode/re-encode path (debugging aid + the bench's A/B switch)."""
  return knobs.get_bool("IGNEOUS_TRANSFER_PASSTHROUGH")


def _resolve_factors(
  vol: Volume,
  mip: int,
  task_shape: Sequence[int],
  num_mips: Optional[int],
  factor: Optional[Sequence[int]],
):
  """The pyramid schedule downsample_and_upload will follow — shared with
  the lease batcher so its one-dispatch device stage produces exactly the
  mips the solo path would."""
  if factor is None:
    factor = DEFAULT_FACTOR
  available = vol.meta.num_mips - mip - 1
  if num_mips is None:
    num_mips = available
  num_mips = min(num_mips, available)
  factors = compute_factors(task_shape, factor, num_mips)

  # chunk-writability guard, per destination mip with that mip's own
  # geometry: a task pointed at pre-existing deep scales the planner
  # didn't create must stop at the last mip whose cutouts land on the
  # chunk grid — unless a single task spans the whole extent (clipped
  # writes at dataset bounds are legal)
  def per_mip(i, cum):
    dest_mip = mip + i + 1
    return (
      vol.meta.chunk_size(dest_mip), vol.meta.bounds(dest_mip).size3()
    )

  return truncate_writable_factors(task_shape, factors, per_mip)


def downsample_and_upload(
  image: np.ndarray,
  bounds: Bbox,
  vol: Volume,
  task_shape: Sequence[int],
  mip: int,
  num_mips: Optional[int] = None,
  factor: Optional[Sequence[int]] = None,
  sparse: bool = False,
  method: str = "auto",
  compress="gzip",
  _mips_out=None,
  sink=None,
):
  """Build the mip pyramid for one cutout and upload every level.

  ``image`` covers ``bounds`` at ``mip``; mips mip+1… are written while
  scales exist in the destination info (or up to num_mips). ``_mips_out``
  injects a pre-computed pyramid (the lease batcher's one-dispatch device
  stage) so only the upload loop runs here — keeping batched chunk bytes
  identical to solo execution. ``sink`` routes chunk encode+put through
  the staged pipeline's upload pool (the caller joins it)."""
  factors = _resolve_factors(vol, mip, task_shape, num_mips, factor)
  if not factors:
    return

  if _mips_out is not None:
    mips_out = _mips_out
  else:
    method = pooling.method_for_layer(vol.layer_type, method)
    # uint64 labels are handled natively (hi/lo uint32 planes on device);
    # hosts with no accelerator dispatch to the native C++ kernels instead
    with telemetry.stage("device_pool"):
      mips_out = pooling.downsample_auto(
        image, factors, len(factors), method=method, sparse=sparse
      )

  cur_bounds = bounds.clone()
  for i, mipped in enumerate(mips_out):
    f = Vec(*factors[i])
    dest_mip = mip + i + 1
    minpt = cur_bounds.minpt // f
    shape3 = mipped.shape[:3]
    cur_bounds = Bbox(minpt, minpt + Vec(*shape3))
    dest_bounds = Bbox.intersection(cur_bounds, vol.meta.bounds(dest_mip))
    sl = tuple(slice(0, int(s)) for s in dest_bounds.size3())
    with telemetry.stage("upload"):
      vol.upload(
        dest_bounds,
        np.asarray(mipped[sl], dtype=vol.dtype),
        mip=dest_mip,
        compress=compress,
        sink=sink,
      )


class TransferTask(RegisteredTask):
  """Copy (and optionally rechunk/re-encode/translate) a cutout, then
  build its downsample pyramid on device."""

  def __init__(
    self,
    src_path: str,
    dest_path: str,
    mip: int,
    shape: Sequence[int],
    offset: Sequence[int],
    fill_missing: bool = False,
    translate: Sequence[int] = (0, 0, 0),
    skip_first: bool = False,
    skip_downsamples: bool = False,
    delete_black_uploads: bool = False,
    background_color: int = 0,
    sparse: bool = False,
    compress="gzip",
    downsample_method: str = "auto",
    num_mips: Optional[int] = None,
    factor: Optional[Sequence[int]] = None,
    agglomerate: bool = False,
    timestamp: Optional[float] = None,
    stop_layer: Optional[int] = None,
  ):
    self.src_path = src_path
    self.dest_path = dest_path
    self.mip = int(mip)
    self.shape = Vec(*shape)
    self.offset = Vec(*offset)
    self.fill_missing = fill_missing
    self.translate = Vec(*translate)
    self.skip_first = skip_first
    self.skip_downsamples = skip_downsamples
    self.delete_black_uploads = delete_black_uploads
    self.background_color = background_color
    self.sparse = sparse
    self.compress = compress
    self.downsample_method = downsample_method
    self.num_mips = num_mips
    self.factor = factor
    # graphene proofread transfers (reference TransferTask agglomerate/
    # timestamp/stop_layer, image.py:434-517): materialize root ids (or
    # L2 ids with stop_layer=2) as of `timestamp` while copying
    self.agglomerate = bool(agglomerate)
    self.timestamp = timestamp
    self.stop_layer = stop_layer
    if timestamp is not None and not (agglomerate or stop_layer is not None):
      raise ValueError(
        "timestamp only applies to agglomerate/stop_layer downloads; "
        "set agglomerate=True (roots) or stop_layer=2 (L2 ids)"
      )

  def trace_attrs(self) -> dict:
    """Task-span attributes for `igneous fleet top/trace`: WHICH cutout
    this was, so slow spans map back to bucket regions."""
    return {
      "dest": self.dest_path,
      "mip": self.mip,
      "bbox": f"{tuple(self.offset)}+{tuple(self.shape)}",
    }

  def _volumes_and_bounds(self):
    src = Volume(
      self.src_path, mip=self.mip, fill_missing=self.fill_missing
    )
    dest = Volume(
      self.dest_path,
      mip=self.mip,
      fill_missing=self.fill_missing,
      delete_black_uploads=self.delete_black_uploads,
      background_color=self.background_color,
    )
    bounds = Bbox(self.offset, self.offset + self.shape)
    bounds = Bbox.intersection(bounds, src.bounds)
    return src, dest, bounds

  def execute(self):
    src, dest, bounds = self._volumes_and_bounds()
    if bounds.empty():
      return
    from ..pipeline import SerialSink

    # solo execution runs the SAME stage code the pipeline schedules —
    # one implementation, one set of bytes
    plan = self._plan_for(src, dest, bounds)
    plan.upload(plan.compute(plan.download()), SerialSink())

  def stage_plan(self):
    """Pipeline decomposition (pipeline.runner.StagePlan): download the
    cutout / build the pyramid / route chunk encode+put through the
    sink. Passthrough-eligible transfers publish a compressed-domain
    plan (stored-byte moves with no decode/compute), so they overlap
    with the rest of the stream instead of barriering it."""
    src, dest, bounds = self._volumes_and_bounds()
    if bounds.empty():
      return _NOOP_PLAN
    return self._plan_for(src, dest, bounds)

  def _plan_for(self, src, dest, bounds: Bbox):
    if self._passthrough_eligible(src, dest, bounds):
      return self._passthrough_plan(src, dest, bounds)
    return self._build_plan(src, dest, bounds)

  def _build_plan(self, src, dest, bounds: Bbox):
    dest_bounds = bounds.translate(self.translate)
    if self.skip_downsamples:
      factors = []
    else:
      factors = _resolve_factors(
        dest, self.mip, self.shape, self.num_mips, self.factor
      )
    reads = {(self.src_path, self.mip)}
    writes = set()
    if not self.skip_first:
      writes.add((self.dest_path, self.mip))
    writes.update((self.dest_path, self.mip + i + 1) for i in range(len(factors)))

    def download():
      with telemetry.stage("download"):
        return src.download(
          bounds, agglomerate=self.agglomerate,
          timestamp=self.timestamp, stop_layer=self.stop_layer,
        )

    def compute(image):
      if not factors:
        return image, None
      method = pooling.method_for_layer(dest.layer_type, self.downsample_method)
      with telemetry.stage("device_pool"):
        mips_out = pooling.downsample_auto(
          image, factors, len(factors), method=method, sparse=self.sparse
        )
      return image, mips_out

    def upload(outputs, sink):
      image, mips_out = outputs
      if not self.skip_first:
        with telemetry.stage("upload"):
          dest.upload(dest_bounds, image, compress=self.compress, sink=sink)
      if not self.skip_downsamples and mips_out is not None:
        downsample_and_upload(
          image,
          dest_bounds,
          dest,
          task_shape=self.shape,
          mip=self.mip,
          num_mips=self.num_mips,
          factor=self.factor,
          sparse=self.sparse,
          method=self.downsample_method,
          compress=self.compress,
          _mips_out=mips_out,
          sink=sink,
        )

    nbytes = int(np.prod([int(v) for v in bounds.size3()]))
    nbytes *= dest.dtype.itemsize * dest.num_channels
    return StagePlan(
      download, compute, upload, reads=reads, writes=writes,
      nbytes_hint=nbytes,
      aligned_writes=self._writes_chunk_aligned(dest, dest_bounds, factors),
    )

  def _writes_chunk_aligned(self, dest, dest_bounds: Bbox, factors) -> bool:
    """True when every bbox upload() will write — the first-mip cutout
    and each pyramid level (the same bounds walk downsample_and_upload
    does, with the kernels' ceil-division output shapes) — is chunk
    aligned or clipped at dataset bounds, i.e. Volume.upload never takes
    its read-modify-write path. Proven-aligned plans may overlap other
    aligned writers of the same (path, mip) in the staged pipeline."""
    def aligned(box: Bbox, mip: int) -> bool:
      if box.empty():
        return True  # writes nothing
      expanded = box.expand_to_chunk_size(
        dest.meta.chunk_size(mip), dest.meta.voxel_offset(mip)
      )
      return Bbox.intersection(expanded, dest.meta.bounds(mip)) == box

    if not self.skip_first and not aligned(dest_bounds, self.mip):
      return False
    cur_min = dest_bounds.minpt
    cur_shape = np.asarray([int(v) for v in dest_bounds.size3()], dtype=np.int64)
    for i, f in enumerate(factors):
      fa = np.asarray([int(v) for v in f], dtype=np.int64)
      cur_min = Vec(*(np.asarray(cur_min, dtype=np.int64) // fa))
      cur_shape = -(-cur_shape // fa)
      dest_mip = self.mip + i + 1
      box = Bbox.intersection(
        Bbox(cur_min, cur_min + Vec(*cur_shape)), dest.meta.bounds(dest_mip)
      )
      if not aligned(box, dest_mip):
        return False
    return True

  def _passthrough_eligible(self, src, dest, bounds: Bbox) -> bool:
    """When the grids, dtype, and encoding line up exactly and no
    resampling/remapping is requested, stored chunk objects can be
    moved without decoding a single voxel (reference image.py:483-497
    `transfer_to` fast path, Palace-style compressed-domain residency)."""
    from ..storage import wire_ext

    mip = self.mip
    sm, dm = src.meta, dest.meta
    return (
      _passthrough_enabled()
      and self.skip_downsamples
      and not self.skip_first  # skip_first + skip_downsamples = no-op
      and not self.agglomerate
      and self.stop_layer is None
      # fill_missing's decode path writes explicit zero chunks for holes;
      # a raw copy would silently leave them missing
      and not self.fill_missing
      # delete_black_uploads' decode path DELETES all-background chunks;
      # a stored-byte move cannot tell black from data without decoding
      and not self.delete_black_uploads
      # unknown wire compression: the decode path raises with context
      and wire_ext(self.compress) is not None
      and tuple(int(v) for v in self.translate) == (0, 0, 0)
      # equal bounds: edge chunks are clamped to the volume bounds in
      # their NAMES — differing extents would file src-clamped chunks
      # under keys dest readers never request
      and src.bounds == dest.bounds
      and not sm.is_sharded(mip) and not dm.is_sharded(mip)
      and bool(np.all(sm.chunk_size(mip) == dm.chunk_size(mip)))
      and bool(np.all(sm.voxel_offset(mip) == dm.voxel_offset(mip)))
      and src.dtype == dest.dtype
      and sm.encoding(mip) == dm.encoding(mip)
      and (
        sm.encoding(mip) != "compressed_segmentation"
        or bool(np.all(sm.cseg_block_size(mip) == dm.cseg_block_size(mip)))
      )
      and bounds == Bbox.intersection(
        bounds.expand_to_chunk_size(sm.chunk_size(mip), sm.voxel_offset(mip)),
        src.bounds,
      )
    )

  def _passthrough_plan(self, src, dest, bounds: Bbox):
    """Compressed-domain transfer: stored chunk bytes move verbatim when
    source wire compression already matches ``compress`` (zero decode,
    zero deflate), and are re-wrapped wire-only otherwise (gunzip +
    re-deflate, still no chunk codec in the path). Writes are whole
    canonical chunk objects — never read-modify-write — so the plan
    proves alignment and overlaps other aligned writers."""
    from ..lib import chunk_bboxes
    from ..storage import CloudFiles, wire_ext

    mip = self.mip
    sm, dm = src.meta, dest.meta
    src_cf = CloudFiles(self.src_path)
    dest_cf = CloudFiles(self.dest_path)
    dest_ext = wire_ext(self.compress)
    chunks = [
      c
      for c in (
        Bbox.intersection(gc, src.bounds)
        for gc in chunk_bboxes(
          bounds, sm.chunk_size(mip), offset=sm.voxel_offset(mip), clamp=False
        )
      )
      if not c.empty()
    ]

    def download():
      keys = [sm.chunk_name(mip, c) for c in chunks]
      with telemetry.stage("passthrough_download"):
        if len(keys) > 1:
          from ..pipeline.encoder import shared_io_pool

          stored = list(shared_io_pool().map(src_cf.get_stored, keys))
        else:
          stored = [src_cf.get_stored(k) for k in keys]
      return stored

    def compute(stored):
      return stored  # compressed-domain: nothing to decode or resample

    def upload(stored, sink):
      from ..storage import compress_bytes, decompress_bytes, wire_ext as wext

      with telemetry.stage("passthrough_upload"):
        for c, (data, method) in zip(chunks, stored):
          if data is None:
            continue  # missing chunks stay missing, like transfer_to
          key = dm.chunk_name(mip, c)

          def put_one(key=key, data=data, method=method):
            telemetry.incr("transfer.passthrough.chunks")
            telemetry.incr("transfer.passthrough.bytes", len(data))
            if wext(method) == dest_ext:
              telemetry.incr("transfer.passthrough.verbatim")
              dest_cf.put_stored(key, data, method)
            else:
              # wire recompress only (the IGNEOUS_SCRATCH_COMPRESS codec
              # table): the chunk encoding itself is never touched
              telemetry.incr("transfer.passthrough.recompressed")
              dest_cf.put_stored(
                key,
                compress_bytes(decompress_bytes(data, method), self.compress),
                self.compress,
              )

          sink.submit(put_one)
      from .. import chunk_cache

      chunk_cache.invalidate(dest.cloudpath, mip)

    nbytes = int(np.prod([int(v) for v in bounds.size3()]))
    nbytes *= dest.dtype.itemsize * dest.num_channels
    return StagePlan(
      download, compute, upload,
      reads={(self.src_path, mip)}, writes={(self.dest_path, mip)},
      nbytes_hint=nbytes, aligned_writes=True,
    )


class DownsampleTask(TransferTask):
  """TransferTask onto itself with the source level skipped
  (reference: image.py:519-550)."""

  def __init__(
    self,
    layer_path: str,
    mip: int,
    shape: Sequence[int],
    offset: Sequence[int],
    fill_missing: bool = False,
    sparse: bool = False,
    delete_black_uploads: bool = False,
    background_color: int = 0,
    compress="gzip",
    downsample_method: str = "auto",
    num_mips: Optional[int] = None,
    factor: Optional[Sequence[int]] = None,
  ):
    super().__init__(
      src_path=layer_path,
      dest_path=layer_path,
      mip=mip,
      shape=shape,
      offset=offset,
      fill_missing=fill_missing,
      skip_first=True,
      sparse=sparse,
      delete_black_uploads=delete_black_uploads,
      background_color=background_color,
      compress=compress,
      downsample_method=downsample_method,
      num_mips=num_mips,
      factor=factor,
    )


class DeleteTask(RegisteredTask):
  """Delete the chunks covering a bbox at mip … mip+num_mips
  (reference: image.py:102-123)."""

  def __init__(
    self,
    layer_path: str,
    shape: Sequence[int],
    offset: Sequence[int],
    mip: int = 0,
    num_mips: int = 0,
  ):
    self.layer_path = layer_path
    self.shape = Vec(*shape)
    self.offset = Vec(*offset)
    self.mip = int(mip)
    self.num_mips = int(num_mips)

  def execute(self):
    vol = Volume(self.layer_path, mip=self.mip)
    bounds = Bbox(self.offset, self.offset + self.shape)
    bounds = Bbox.intersection(bounds, vol.bounds)
    if bounds.empty():
      return
    for i in range(self.num_mips + 1):
      mip = self.mip + i
      if mip >= vol.meta.num_mips:
        break
      mip_bounds = vol.meta.bbox_to_mip(bounds, self.mip, mip)
      mip_bounds = mip_bounds.expand_to_chunk_size(
        vol.meta.chunk_size(mip), vol.meta.voxel_offset(mip)
      ).clamp(vol.meta.bounds(mip))
      vol.delete(mip_bounds, mip=mip)


class BlackoutTask(RegisteredTask):
  """Overwrite a bbox with a constant value (reference: image.py:124-136)."""

  def __init__(
    self,
    cloudpath: str,
    mip: int,
    shape: Sequence[int],
    offset: Sequence[int],
    value: int = 0,
    non_aligned_writes: bool = False,
  ):
    self.cloudpath = cloudpath
    self.mip = int(mip)
    self.shape = Vec(*shape)
    self.offset = Vec(*offset)
    self.value = value
    self.non_aligned_writes = non_aligned_writes

  def execute(self):
    vol = Volume(
      self.cloudpath, mip=self.mip, non_aligned_writes=self.non_aligned_writes
    )
    bounds = Bbox.intersection(
      Bbox(self.offset, self.offset + self.shape), vol.bounds
    )
    if bounds.empty():
      return
    img = np.full(
      tuple(int(v) for v in bounds.size3()) + (vol.num_channels,),
      self.value,
      dtype=vol.dtype,
    )
    vol.upload(bounds, img)


class TouchTask(RegisteredTask):
  """Read a bbox with fill_missing disabled to verify data integrity
  (reference: image.py:137-143)."""

  def __init__(self, cloudpath: str, mip: int, shape: Sequence[int], offset: Sequence[int]):
    self.cloudpath = cloudpath
    self.mip = int(mip)
    self.shape = Vec(*shape)
    self.offset = Vec(*offset)

  def execute(self):
    vol = Volume(self.cloudpath, mip=self.mip, fill_missing=False)
    bounds = Bbox.intersection(
      Bbox(self.offset, self.offset + self.shape), vol.bounds
    )
    vol.download(bounds)


class QuantizeTask(RegisteredTask):
  """float affinity channel → uint8 (reference: image.py:145-163)."""

  def __init__(
    self,
    source_layer_path: str,
    dest_layer_path: str,
    shape: Sequence[int],
    offset: Sequence[int],
    mip: int = 0,
    fill_missing: bool = False,
  ):
    self.source_layer_path = source_layer_path
    self.dest_layer_path = dest_layer_path
    self.shape = Vec(*shape)
    self.offset = Vec(*offset)
    self.mip = int(mip)
    self.fill_missing = fill_missing

  def execute(self):
    src = Volume(self.source_layer_path, mip=self.mip, fill_missing=self.fill_missing)
    dest = Volume(self.dest_layer_path, mip=self.mip)
    bounds = Bbox.intersection(
      Bbox(self.offset, self.offset + self.shape), src.bounds
    )
    if bounds.empty():
      return
    image = src.download(bounds)[..., :1]  # first channel only
    image = np.clip(image.astype(np.float32) * 255.0, 0, 255).astype(np.uint8)
    dest.upload(bounds, image)
