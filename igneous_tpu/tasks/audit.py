"""Integrity audit tasks (ISSUE 16): replay a campaign's chunk grid and
verify every expected output against the write envelope.

An :class:`IntegrityAuditTask` covers one grid cell of one mip: it
enumerates the stored chunks that cell must contain — the SAME
grid-alignment math ``Volume.download`` and the creation factories use,
so "expected" can never drift from "produced" — and classifies each:

  missing          object absent from storage
  decode_error     stored wire bytes fail decompression (torn gzip, …)
  digest_mismatch  bytes decode but differ from the manifest digest
                   recorded at write time (bit rot in raw-stored data,
                   or any at-rest mutation that preserved framing)

Findings land as one deterministic JSONL file per (mip, cell) under the
report dir — re-running a task overwrites its own report, so audits are
idempotent under at-least-once delivery and a heal round simply re-runs
the same grid. Chunks present-and-valid but absent from any manifest
(campaigns that predate the envelope, or ``IGNEOUS_INTEGRITY=off``
runs) are tallied as ``unmanifested``, not failed: presence + decode
still got verified.
"""

from __future__ import annotations

import json

from ..lib import Bbox, Vec, chunk_bboxes
from ..queues.registry import RegisteredTask
from ..storage import COMPRESSION_EXTS, CloudFiles, decompress_bytes
from ..volume import Volume
from .. import integrity, telemetry


def expected_chunks(vol: Volume, bounds: Bbox, mip: int):
  """Grid-aligned, bounds-clamped chunk bboxes inside ``bounds`` — the
  download path's enumeration, reused verbatim as the audit oracle."""
  full = vol.meta.bounds(mip)
  inner = Bbox.intersection(bounds, full)
  return [
    c
    for c in (
      Bbox.intersection(gc, full)
      for gc in chunk_bboxes(
        inner,
        vol.meta.chunk_size(mip),
        offset=vol.meta.voxel_offset(mip),
        clamp=False,
      )
    )
    if not c.empty()
  ]


def report_name(mip: int, offset) -> str:
  x, y, z = (int(v) for v in offset)
  return f"findings_{mip}_{x}_{y}_{z}.jsonl"


class IntegrityAuditTask(RegisteredTask):
  """Verify presence + decode + manifest digest for every chunk of one
  grid cell at one mip; write a deterministic findings report."""

  def __init__(
    self,
    layer_path: str,
    mip: int,
    shape,
    offset,
    report_dir: str,
    check_digest: bool = True,
    require_present: bool = True,
  ):
    self.layer_path = layer_path
    self.mip = mip
    self.shape = shape
    self.offset = offset
    self.report_dir = report_dir
    self.check_digest = check_digest
    self.require_present = require_present

  def execute(self):
    vol = Volume(self.layer_path, mip=self.mip, bounded=False)
    bounds = Bbox(Vec(*self.offset), Vec(*self.offset) + Vec(*self.shape))
    chunks = expected_chunks(vol, bounds, self.mip)
    cf = CloudFiles(self.layer_path)
    manifest = (
      integrity.load_manifest(self.layer_path, prefix=vol.meta.key(self.mip))
      if self.check_digest
      else {}
    )

    findings = []
    unmanifested = 0
    for chunk_bbx in chunks:
      key = vol.meta.chunk_name(self.mip, chunk_bbx)
      stored, method = cf.get_stored(key)
      if stored is None:
        if self.require_present:
          findings.append(self._finding("missing", key, chunk_bbx))
        continue
      try:
        decompress_bytes(stored, method)
      except Exception as e:
        findings.append(self._finding(
          "decode_error", key, chunk_bbx,
          reason=f"{type(e).__name__}: {e}",
        ))
        continue
      if not self.check_digest:
        continue
      rec = manifest.get(key + COMPRESSION_EXTS[method])
      if rec is None:
        unmanifested += 1
        continue
      actual = integrity.digest_hex(stored)
      if actual != rec["digest"]:
        findings.append(self._finding(
          "digest_mismatch", key, chunk_bbx,
          expected=rec["digest"], actual=actual,
        ))

    telemetry.incr("integrity.audit.chunks", len(chunks))
    if findings:
      telemetry.incr("integrity.audit.findings", len(findings))
    summary = {
      "kind": "summary",
      "mip": int(self.mip),
      "chunks": len(chunks),
      "findings": len(findings),
      "unmanifested": unmanifested,
    }
    body = "".join(
      json.dumps(rec, sort_keys=True) + "\n"
      for rec in [summary] + findings
    )
    CloudFiles(self.report_dir).put(
      report_name(self.mip, self.offset), body.encode("utf8"), compress=None
    )
    return summary

  def _finding(self, kind: str, key: str, chunk_bbx: Bbox, **extra) -> dict:
    out = {
      "kind": kind,
      "key": key,
      "mip": int(self.mip),
      "bbox": chunk_bbx.to_list(),
    }
    out.update(extra)
    return out

  def trace_attrs(self) -> dict:
    return {"mip": int(self.mip), "layer": self.layer_path[-60:]}
