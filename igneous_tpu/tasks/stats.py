"""Voxel statistics + spatial index + reorder tasks.

Reference parity:
  CountVoxelsTask      /root/reference/igneous/tasks/image/image.py:849-884
  accumulate_voxel_counts  igneous/task_creation/image.py:1975-2030
  SpatialIndexTask     igneous/tasks/spatial_index.py:22-75
  ReorderTask          igneous/tasks/image/image.py:552
"""

from __future__ import annotations

import struct
from collections import defaultdict
from typing import Dict, Optional, Sequence

import numpy as np
from scipy import ndimage

from ..lib import Bbox, Vec
from ..mesh_io import FragMap
from ..queues.registry import RegisteredTask
from ..storage import CloudFiles
from ..volume import Volume
from ..ops import remap as fastremap

VOXEL_COUNT_DIR = "stats/voxel_counts"


class CountVoxelsTask(RegisteredTask):
  """Per-task label→voxel-count census, uploaded as one JSON."""

  def __init__(
    self,
    cloudpath: str,
    shape: Sequence[int],
    offset: Sequence[int],
    mip: int = 0,
    fill_missing: bool = False,
    agglomerate: bool = False,
    timestamp=None,
  ):
    self.cloudpath = cloudpath
    self.shape = Vec(*shape)
    self.offset = Vec(*offset)
    self.mip = int(mip)
    self.fill_missing = fill_missing
    # graphene volumes: census the proofread ROOT ids as of timestamp
    # (reference CountVoxelsTask agglomerate passthrough)
    self.agglomerate = bool(agglomerate)
    self.timestamp = timestamp

  def execute(self):
    vol = Volume(self.cloudpath, mip=self.mip, fill_missing=self.fill_missing,
                 bounded=False)
    bounds = Bbox.intersection(
      Bbox(self.offset, self.offset + self.shape), vol.bounds
    )
    if bounds.empty():
      return
    img = vol.download(
      bounds, agglomerate=self.agglomerate, timestamp=self.timestamp
    )[..., 0]
    labels, counts = fastremap.unique(img, return_counts=True)
    cf = CloudFiles(vol.cloudpath)
    cf.put_json(
      f"{VOXEL_COUNT_DIR}/{self.mip}/{bounds.to_filename()}",
      {str(int(l)): int(c) for l, c in zip(labels, counts)},
      compress="gzip",
    )


def accumulate_voxel_counts(
  cloudpath: str, mip: int = 0, compress: str = "gzip",
  additional_output: Optional[str] = None,
) -> Dict[int, int]:
  """Single-machine reduce: sum all census JSONs → ``voxel_counts.im``
  (a FragMap of uint64 counts — the mapbuffer-format equivalent of the
  reference's IntMap, task_creation/image.py:1975-2030). Returns totals.
  ``additional_output`` also writes the FragMap to a local path (the
  reference CLI's -o, cli.py:527-540)."""
  cf = CloudFiles(cloudpath)
  totals: Dict[int, int] = defaultdict(int)
  for key in cf.list(f"{VOXEL_COUNT_DIR}/{mip}/"):
    doc = cf.get_json(key)
    if not doc:
      continue
    for label, count in doc.items():
      totals[int(label)] += int(count)
  payload = {
    label: struct.pack("<Q", count) for label, count in totals.items()
  }
  blob = FragMap.tobytes(payload)
  cf.put(f"{VOXEL_COUNT_DIR}/{mip}/voxel_counts.im", blob, compress=compress)
  if additional_output:
    with open(additional_output, "wb") as f:
      f.write(blob)
  return dict(totals)


def load_voxel_counts(cloudpath: str, mip: int = 0) -> Optional[FragMap]:
  cf = CloudFiles(cloudpath)
  data = cf.get(f"{VOXEL_COUNT_DIR}/{mip}/voxel_counts.im")
  return None if data is None else FragMap.frombytes(data)


def globally_small_labels(
  cloudpath: str, mip: int, labels, threshold: float,
) -> list:
  """Labels whose GLOBAL voxel count (from the voxel_counts.im census)
  falls below ``threshold`` — the dust_global primitive shared by
  SkeletonTask and MeshTask (reference tasks/skeleton.py:722-755 and
  tasks/mesh/mesh.py:313-355). Raises if the census has not been built."""
  counts = load_voxel_counts(cloudpath, mip)
  if counts is None:
    raise ValueError(
      "dust_global requires the voxel-count census: run "
      "`igneous-tpu image voxels count` then `... voxels sum` (or "
      "tasks.stats.accumulate_voxel_counts) on this layer first."
    )
  small = []
  for label in labels:
    label = int(label)
    if label == 0:
      continue
    blob = counts.get(label)
    total = struct.unpack("<Q", blob)[0] if blob else 0
    if total < threshold:
      small.append(label)
  return small


class SpatialIndexTask(RegisteredTask):
  """(Re)build one grid cell's .spatial file from the segmentation
  (reference igneous/tasks/spatial_index.py:22-75)."""

  def __init__(
    self,
    cloudpath: str,
    prefix: str,
    shape: Sequence[int],
    offset: Sequence[int],
    mip: int = 0,
    fill_missing: bool = False,
  ):
    self.cloudpath = cloudpath
    self.prefix = prefix
    self.shape = Vec(*shape)
    self.offset = Vec(*offset)
    self.mip = int(mip)
    self.fill_missing = fill_missing

  def execute(self):
    from ..spatial_index import SpatialIndex

    vol = Volume(self.cloudpath, mip=self.mip, fill_missing=self.fill_missing,
                 bounded=False)
    bounds = Bbox.intersection(
      Bbox(self.offset, self.offset + self.shape), vol.bounds
    )
    if bounds.empty():
      return
    img = vol.download(bounds)[..., 0]
    dense, mapping = fastremap.renumber(img)
    slices = ndimage.find_objects(dense.astype(np.int32))
    res = np.asarray(vol.resolution, dtype=np.int64)

    label_bounds = {}
    for new_id, sl in enumerate(slices, start=1):
      if sl is None:
        continue
      mn = (np.asarray([s.start for s in sl]) + np.asarray(bounds.minpt)) * res
      mx = (np.asarray([s.stop for s in sl]) + np.asarray(bounds.minpt)) * res
      label_bounds[mapping[new_id]] = Bbox(mn, mx)

    physical = Bbox(bounds.minpt * res, bounds.maxpt * res)
    SpatialIndex(CloudFiles(vol.cloudpath), self.prefix).put(
      physical, label_bounds
    )


class ReorderTask(RegisteredTask):
  """Copy z-slices into a new z order (reference image.py:552):
  dest[z] = src[mapping[z]] for the task's z range."""

  def __init__(
    self,
    src_path: str,
    dest_path: str,
    mip: int,
    z_start: int,
    z_end: int,
    mapping: Dict,
    fill_missing: bool = False,
    compress="gzip",
    delete_black_uploads: bool = False,
    background_color: int = 0,
  ):
    self.src_path = src_path
    self.dest_path = dest_path
    self.mip = int(mip)
    self.z_start = int(z_start)
    self.z_end = int(z_end)
    self.mapping = {int(k): int(v) for k, v in mapping.items()}
    self.fill_missing = fill_missing
    self.compress = compress
    self.delete_black_uploads = bool(delete_black_uploads)
    self.background_color = int(background_color)

  def execute(self):
    src = Volume(self.src_path, mip=self.mip, fill_missing=self.fill_missing)
    dest = Volume(
      self.dest_path, mip=self.mip,
      delete_black_uploads=self.delete_black_uploads,
      background_color=self.background_color,
    )
    bounds = src.bounds
    for z in range(self.z_start, self.z_end):
      src_z = self.mapping.get(z, z)
      sl = Bbox(
        (bounds.minpt.x, bounds.minpt.y, src_z),
        (bounds.maxpt.x, bounds.maxpt.y, src_z + 1),
      )
      dl = Bbox(
        (bounds.minpt.x, bounds.minpt.y, z),
        (bounds.maxpt.x, bounds.maxpt.y, z + 1),
      )
      dest.upload(dl, src.download(sl), compress=self.compress)
