"""InferenceTask: halo'd download → jitted JAX model apply → overlap
blend → optional argmax/quantize → Precomputed output (ISSUE 10).

The Chunkflow workload shape (PAPERS.md): each grid task downloads its
core cutout EXPANDED by a halo so every output voxel sees full model
context, runs the patch engine (infer.engine) over the halo'd cutout,
crops the halo back off, and uploads only the core — so adjacent tasks
never write overlapping voxels and the write set stays provably
chunk-aligned for the staged pipeline's overlap rules.

Byte determinism rides the engine's canonical accumulation order plus
the pipeline invariant that compute stages run in task order on the
caller thread in both pipelined and serial modes.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..lib import Bbox, Vec
from ..queues.registry import RegisteredTask
from ..volume import Volume
from ..pipeline import StagePlan
from .. import telemetry

POSTPROCESS_MODES = ("none", "quantize", "argmax")

# empty-cutout tasks stage as no-ops (same contract as tasks/image.py)
_NOOP_PLAN = StagePlan(lambda: None, lambda p: None, lambda o, s: None)


class InferenceTask(RegisteredTask):
  """Patch-wise conv-net inference over one grid cutout.

  ``model_path`` names a model saved by ``infer.registry.save_model``
  (model.json + params.npz on any storage backend); patch size and
  overlap come from the model spec, so the wire payload stays small and
  every worker tiles identically. ``halo`` voxels of extra context are
  downloaded on every face (clamped reads fill background outside the
  volume) and cropped before upload.

  ``postprocess``: ``none`` (float32 channels), ``quantize`` (clip to
  [0,1], scale to uint8), ``argmax`` (uint8 channel argmax — a
  segmentation-style output).
  """

  def __init__(
    self,
    src_path: str,
    dest_path: str,
    model_path: str,
    mip: int,
    shape: Sequence[int],
    offset: Sequence[int],
    halo: Sequence[int] = (0, 0, 0),
    fill_missing: bool = False,
    batch_size: int = 4,
    postprocess: str = "none",
    compress="gzip",
  ):
    self.src_path = src_path
    self.dest_path = dest_path
    self.model_path = model_path
    self.mip = int(mip)
    self.shape = Vec(*shape)
    self.offset = Vec(*offset)
    self.halo = Vec(*halo)
    self.fill_missing = fill_missing
    self.batch_size = int(batch_size)
    self.postprocess = postprocess
    self.compress = compress
    if postprocess not in POSTPROCESS_MODES:
      raise ValueError(
        f"postprocess must be one of {POSTPROCESS_MODES}: {postprocess!r}"
      )

  def trace_attrs(self) -> dict:
    return {
      "dest": self.dest_path,
      "model": self.model_path,
      "mip": self.mip,
      "bbox": f"{tuple(self.offset)}+{tuple(self.shape)}",
    }

  def _volumes_and_bounds(self):
    # bounded=False: the halo legitimately pokes outside the volume at
    # edges; clamped regions come back background-filled, which is the
    # halo contract (context decays to background, core is unaffected)
    src = Volume(
      self.src_path, mip=self.mip, bounded=False,
      fill_missing=self.fill_missing,
    )
    dest = Volume(self.dest_path, mip=self.mip)
    core = Bbox(self.offset, self.offset + self.shape)
    core = Bbox.intersection(core, src.bounds)
    core = Bbox.intersection(core, dest.bounds)
    return src, dest, core

  def execute(self):
    from ..pipeline import SerialSink

    plan = self.stage_plan()
    plan.upload(plan.compute(plan.download()), SerialSink())

  def stage_plan(self):
    src, dest, core = self._volumes_and_bounds()
    if core.empty():
      return _NOOP_PLAN
    halo = Vec(*[int(v) for v in self.halo])
    halo_bounds = Bbox(core.minpt - halo, core.maxpt + halo)
    core_size = [int(v) for v in core.size3()]

    def download():
      with telemetry.stage("download"):
        return src.download(halo_bounds)

    def compute(image):
      from ..infer import engine as infer_engine
      from ..infer import registry as infer_registry
      from ..observability.device import LEDGER

      model = infer_registry.load_model(self.model_path)
      with telemetry.stage("device_infer"):
        out, stats = infer_engine.infer_cutout(
          model, image, batch_size=self.batch_size,
        )
      # fast-path tally (ISSUE 10 satellite): real patches rode the
      # batched dispatch; zero-padded slots are the ragged-batching
      # loss — igneous_device_fastpath_ratio now prices it
      LEDGER.record_fastpath(
        batched=stats["patches"], host=stats["padded_slots"]
      )
      hx, hy, hz = (int(v) for v in halo)
      out = out[hx:hx + core_size[0], hy:hy + core_size[1],
                hz:hz + core_size[2]]
      return self._postprocess(out, dest)

    def upload(out, sink):
      with telemetry.stage("upload"):
        dest.upload(core, out, compress=self.compress, sink=sink)

    halo_size = [int(v) for v in halo_bounds.size3()]
    nbytes = int(np.prod(halo_size)) * 4 * src.num_channels
    nbytes += int(np.prod(core_size)) * dest.dtype.itemsize * dest.num_channels
    return StagePlan(
      download, compute, upload,
      reads={(self.src_path, self.mip)},
      writes={(self.dest_path, self.mip)},
      nbytes_hint=nbytes,
      aligned_writes=self._writes_chunk_aligned(dest, core),
    )

  def _postprocess(self, out: np.ndarray, dest) -> np.ndarray:
    if self.postprocess == "quantize":
      out = (np.clip(out, 0.0, 1.0) * 255.0).astype(np.uint8)
    elif self.postprocess == "argmax":
      out = np.argmax(out, axis=3).astype(np.uint8)[..., np.newaxis]
    return out.astype(dest.dtype, copy=False)

  def _writes_chunk_aligned(self, dest, core: Bbox) -> bool:
    """Same proof as TransferTask: the single core write is aligned or
    clipped at dataset bounds, so Volume.upload never read-modify-writes
    and proven-aligned plans may overlap in the staged pipeline."""
    if core.empty():
      return True
    expanded = core.expand_to_chunk_size(
      dest.meta.chunk_size(self.mip), dest.meta.voxel_offset(self.mip)
    )
    return Bbox.intersection(expanded, dest.meta.bounds(self.mip)) == core
