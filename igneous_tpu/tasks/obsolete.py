"""Legacy-compatibility tasks kept importable.

Reference parity: /root/reference/igneous/tasks/image/obsolete.py
  HyperSquareConsensusTask (:49-133)  Eyewire consensus remapping
  WatershedRemapTask (:134-194)       npy remap-table application
  MaskAffinitymapTask (:195-286)      zero affinities outside a mask
  InferenceTask (:287+)               patch-wise convnet inference

These exist so pipelines written against the reference's task names keep
deserializing and running. InferenceTask runs a user-registered JAX model
function (register_inference_model) patch-wise on device — the ChunkFlow
-style capability with the TPU as the backend.
"""

from __future__ import annotations

import io
from typing import Callable, Dict, Optional, Sequence

import numpy as np

from ..lib import Bbox, Vec
from ..queues.registry import RegisteredTask
from ..storage import CloudFiles
from ..volume import Volume
from ..ops import remap as fastremap


class HyperSquareConsensusTask(RegisteredTask):
  """Apply an Eyewire-style consensus map (segment ids → consensus ids)
  stored as JSON {volume_id: {segid: consensus_id}}."""

  def __init__(
    self,
    src_path: str,
    dest_path: str,
    consensus_map_path: str,
    shape: Sequence[int],
    offset: Sequence[int],
    mip: int = 0,
  ):
    self.src_path = src_path
    self.dest_path = dest_path
    self.consensus_map_path = consensus_map_path
    self.shape = Vec(*shape)
    self.offset = Vec(*offset)
    self.mip = int(mip)

  def execute(self):
    src = Volume(self.src_path, mip=self.mip, bounded=False)
    dest = Volume(self.dest_path, mip=self.mip)
    bounds = Bbox.intersection(
      Bbox(self.offset, self.offset + self.shape), src.bounds
    )
    if bounds.empty():
      return
    root, _, key = self.consensus_map_path.rpartition("/")
    data = CloudFiles(root).get(key)
    if data is None:
      raise FileNotFoundError(
        f"consensus map not found: {self.consensus_map_path}"
      )
    import json as json_mod

    mapping_doc = json_mod.loads(data.decode("utf8"))
    table: Dict[int, int] = {}
    for seg_map in mapping_doc.values():
      for segid, consensus in seg_map.items():
        table[int(segid)] = int(consensus)
    img = src.download(bounds)[..., 0]
    out = fastremap.remap(img, {**table, 0: 0}, preserve_missing_labels=True)
    dest.upload(bounds, out.astype(dest.dtype))


class WatershedRemapTask(RegisteredTask):
  """Apply a .npy remap array (index = watershed id, value = new id)."""

  def __init__(
    self,
    map_path: str,
    src_path: str,
    dest_path: str,
    shape: Sequence[int],
    offset: Sequence[int],
    mip: int = 0,
  ):
    self.map_path = map_path
    self.src_path = src_path
    self.dest_path = dest_path
    self.shape = Vec(*shape)
    self.offset = Vec(*offset)
    self.mip = int(mip)

  def execute(self):
    src = Volume(self.src_path, mip=self.mip, bounded=False)
    dest = Volume(self.dest_path, mip=self.mip)
    bounds = Bbox.intersection(
      Bbox(self.offset, self.offset + self.shape), src.bounds
    )
    if bounds.empty():
      return
    pth = self.map_path
    if "://" in pth:
      proto_root, _, key = pth.rpartition("/")
      data = CloudFiles(proto_root).get(key)
      if data is None:
        raise FileNotFoundError(f"remap table not found: {pth}")
      table = np.load(io.BytesIO(data))
    else:
      table = np.load(pth)
    img = src.download(bounds)[..., 0]
    out = table[img.astype(np.int64)]
    dest.upload(bounds, out.astype(dest.dtype))


class MaskAffinitymapTask(RegisteredTask):
  """Zero affinity channels wherever the mask layer is zero."""

  def __init__(
    self,
    aff_path: str,
    mask_path: str,
    dest_path: str,
    shape: Sequence[int],
    offset: Sequence[int],
    mip: int = 0,
    mask_mip: int = 0,
  ):
    self.aff_path = aff_path
    self.mask_path = mask_path
    self.dest_path = dest_path
    self.shape = Vec(*shape)
    self.offset = Vec(*offset)
    self.mip = int(mip)
    self.mask_mip = int(mask_mip)

  def execute(self):
    aff = Volume(self.aff_path, mip=self.mip, bounded=False)
    mask_vol = Volume(self.mask_path, mip=self.mask_mip, bounded=False)
    dest = Volume(self.dest_path, mip=self.mip)
    bounds = Bbox.intersection(
      Bbox(self.offset, self.offset + self.shape), aff.bounds
    )
    if bounds.empty():
      return
    img = aff.download(bounds)
    mask_bounds = mask_vol.meta.bbox_to_mip(bounds, self.mip, self.mask_mip)
    mask = mask_vol.download(mask_bounds)[..., 0]
    if mask.shape != img.shape[:3]:  # differing mips: upsample by repetition
      reps = [int(np.ceil(a / b)) for a, b in zip(img.shape[:3], mask.shape)]
      mask = np.kron(mask, np.ones(reps, dtype=mask.dtype))[
        : img.shape[0], : img.shape[1], : img.shape[2]
      ]
    img[mask == 0] = 0
    dest.upload(bounds, img)


_INFERENCE_MODELS: Dict[str, Callable] = {}


def register_inference_model(name: str, fn: Callable):
  """fn(patch: np.ndarray[x,y,z,c_in]) -> np.ndarray[x,y,z,c_out].

  The patch-wise convnet hook for InferenceTask — typically a jitted JAX
  model so the TPU runs the convolutions."""
  _INFERENCE_MODELS[name] = fn


class LegacyInferenceTask(RegisteredTask):
  """Patch-wise model inference with overlap-blend (ChunkFlow-style,
  reference obsolete.py:287+). Patches overlap by ``overlap`` voxels and
  are linearly blended."""

  def __init__(
    self,
    src_path: str,
    dest_path: str,
    model_name: str,
    shape: Sequence[int],
    offset: Sequence[int],
    patch_size: Sequence[int] = (64, 64, 32),
    overlap: Sequence[int] = (8, 8, 4),
    mip: int = 0,
    fill_missing: bool = False,
  ):
    self.src_path = src_path
    self.dest_path = dest_path
    self.model_name = model_name
    self.shape = Vec(*shape)
    self.offset = Vec(*offset)
    self.patch_size = Vec(*patch_size)
    self.overlap = Vec(*overlap)
    self.mip = int(mip)
    self.fill_missing = fill_missing

  def execute(self):
    if self.model_name not in _INFERENCE_MODELS:
      raise KeyError(
        f"No inference model {self.model_name!r}; call "
        "register_inference_model() in the worker before polling."
      )
    model = _INFERENCE_MODELS[self.model_name]
    src = Volume(self.src_path, mip=self.mip, bounded=False,
                 fill_missing=self.fill_missing)
    dest = Volume(self.dest_path, mip=self.mip)
    bounds = Bbox.intersection(
      Bbox(self.offset, self.offset + self.shape), src.bounds
    )
    if bounds.empty():
      return
    img = src.download(bounds).astype(np.float32)

    ps = np.asarray(self.patch_size, dtype=np.int64)
    ov = np.asarray(self.overlap, dtype=np.int64)
    stride = np.maximum(ps - ov, 1)
    size = np.asarray(img.shape[:3], dtype=np.int64)

    out = None
    weight = np.zeros(img.shape[:3] + (1,), dtype=np.float32)
    starts = [
      np.unique(np.clip(np.arange(0, size[a], stride[a]), 0,
                        max(size[a] - ps[a], 0)))
      for a in range(3)
    ]
    for x0 in starts[0]:
      for y0 in starts[1]:
        for z0 in starts[2]:
          sl = tuple(
            slice(int(s), int(min(s + p, e)))
            for s, p, e in zip((x0, y0, z0), ps, size)
          )
          patch = img[sl]
          result = np.asarray(model(patch), dtype=np.float32)
          if out is None:
            out = np.zeros(img.shape[:3] + (result.shape[3],), np.float32)
          out[sl[0], sl[1], sl[2], :] += result
          weight[sl] += 1.0
    out /= np.maximum(weight, 1e-6)
    dest.upload(bounds, out.astype(dest.dtype))


# Superseded by tasks.inference.InferenceTask (ISSUE 10): the first-class
# task owns the `InferenceTask` wire name now. This alias keeps the
# in-process (register_inference_model) flavor importable under its old
# name; the class registers on the wire as LegacyInferenceTask.
InferenceTask = LegacyInferenceTask
