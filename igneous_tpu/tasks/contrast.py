"""Contrast correction tasks: luminance levels, histogram stretch, CLAHE.

Reference parity: /root/reference/igneous/tasks/image/image.py
  LuminanceLevelsTask (:345-432)  per-z sampled histograms → levels JSONs
  ContrastNormalizationTask (:211-342)  percentile stretch using levels
  CLAHETask (:164-209)  per-z-slice CLAHE (OpenCV), overlap-padded

The two-phase map/merge shape (histogram → normalize) is the pipeline's
"luminance" instance of SURVEY.md §2.4 item 3.
"""

from __future__ import annotations

import json
from typing import Optional, Sequence

import numpy as np

from ..lib import Bbox, Vec
from ..queues.registry import RegisteredTask
from ..storage import CloudFiles
from ..volume import Volume

LEVELS_BINS = 256


def levels_key(mip: int) -> str:
  return f"levels/{mip}"


def _bin_width(dtype) -> int:
  """Histogram bin width covering the full integer dtype range with
  LEVELS_BINS bins (uint8 → 1, uint16 → 256, …)."""
  dtype = np.dtype(dtype)
  if dtype.kind not in "ui":
    raise ValueError(
      f"luminance histograms require an integer layer, got {dtype}"
    )
  return max((np.iinfo(dtype).max + 1) // LEVELS_BINS, 1)


class LuminanceLevelsTask(RegisteredTask):
  """Sample a fraction of one z-range's pixels; upload per-z histograms."""

  def __init__(
    self,
    src_path: str,
    levels_path_: Optional[str] = None,
    shape: Sequence[int] = (2048, 2048, 1),
    offset: Sequence[int] = (0, 0, 0),
    mip: int = 0,
    coverage_factor: float = 0.01,
    fill_missing: bool = False,
  ):
    self.src_path = src_path
    self.levels_path_ = levels_path_
    self.shape = Vec(*shape)
    self.offset = Vec(*offset)
    self.mip = int(mip)
    self.coverage_factor = float(coverage_factor)
    self.fill_missing = fill_missing

  PATCH = 256  # xy patch edge for sampled downloads

  def execute(self):
    vol = Volume(self.src_path, mip=self.mip, fill_missing=self.fill_missing,
                 bounded=False)
    bounds = Bbox.intersection(
      Bbox(self.offset, self.offset + self.shape), vol.bounds
    )
    if bounds.empty():
      return
    cf = CloudFiles(self.levels_path_ or vol.cloudpath)
    width = _bin_width(vol.dtype)
    rng = np.random.default_rng(int(self.offset.z))  # deterministic sampling

    # sample patch LOCATIONS before downloading — coverage_factor bounds
    # the bytes transferred, not just the pixels histogrammed
    # (reference LuminanceLevelsTask's sampling design, image.py:345-432)
    sx, sy, sz = (int(v) for v in bounds.size3())
    area = sx * sy
    patch = min(self.PATCH, sx, sy)
    n_patches = max(int(np.ceil(area * self.coverage_factor / patch**2)), 1)
    xs = rng.integers(0, max(sx - patch, 0) + 1, size=n_patches)
    ys = rng.integers(0, max(sy - patch, 0) + 1, size=n_patches)

    # download each sampled patch ONCE as a full z column (a 1-z-thick
    # read would decode the whole chunk-z-thick chunk per slice), but
    # STREAM the columns: accumulate per-z histograms and drop each
    # column before the next download so peak memory stays one column,
    # not coverage_factor x the slab
    hists = np.zeros((sz, LEVELS_BINS), dtype=np.int64)
    n_samples = 0
    for px, py in zip(xs, ys):
      col_box = Bbox(
        bounds.minpt + (int(px), int(py), 0),
        bounds.minpt + (int(px) + patch, int(py) + patch, sz),
      )
      col = vol.download(col_box)[..., 0]
      n_samples += col.shape[0] * col.shape[1]
      binned = (col // col.dtype.type(width)).astype(np.int64)
      for dz in range(sz):
        hists[dz] += np.bincount(
          binned[:, :, dz].reshape(-1), minlength=LEVELS_BINS,
        )[:LEVELS_BINS]
    for dz in range(sz):
      z = int(bounds.minpt.z) + dz
      cf.put_json(
        f"{levels_key(self.mip)}/{z}",
        {
          "levels": hists[dz].tolist(),
          "bin_width": int(width),
          "patch_size": [patch, patch, 1],
          "num_samples": int(n_samples),
          "coverage_ratio": self.coverage_factor,
        },
      )


def compute_stretch_bounds(levels: np.ndarray, clip_fraction: float):
  """(low, high) bin indices clipping `clip_fraction` of mass per tail."""
  total = levels.sum()
  if total == 0:
    return 0, LEVELS_BINS - 1
  cdf = np.cumsum(levels) / total
  lower = int(np.searchsorted(cdf, clip_fraction))
  upper = int(np.searchsorted(cdf, 1.0 - clip_fraction))
  upper = min(max(upper, lower + 1), LEVELS_BINS - 1)
  return lower, upper


class ContrastNormalizationTask(RegisteredTask):
  """Histogram-stretch using the levels files (reference :211-342)."""

  def __init__(
    self,
    src_path: str,
    dest_path: str,
    shape: Sequence[int],
    offset: Sequence[int],
    mip: int = 0,
    clip_fraction: float = 0.01,
    fill_missing: bool = False,
    translate: Sequence[int] = (0, 0, 0),
    minval: int = 0,
    maxval: int = 255,
    levels_path_: Optional[str] = None,
  ):
    self.src_path = src_path
    self.dest_path = dest_path
    self.shape = Vec(*shape)
    self.offset = Vec(*offset)
    self.mip = int(mip)
    self.clip_fraction = float(clip_fraction)
    self.fill_missing = fill_missing
    self.translate = Vec(*translate)
    self.minval = int(minval)
    self.maxval = int(maxval)
    self.levels_path_ = levels_path_

  def execute(self):
    src = Volume(self.src_path, mip=self.mip, fill_missing=self.fill_missing,
                 bounded=False)
    dest = Volume(self.dest_path, mip=self.mip)
    bounds = Bbox.intersection(
      Bbox(self.offset, self.offset + self.shape), src.bounds
    )
    if bounds.empty():
      return
    img = src.download(bounds).astype(np.float32)
    cf = CloudFiles(self.levels_path_ or src.cloudpath)

    for dz in range(img.shape[2]):
      z = int(bounds.minpt.z) + dz
      doc = cf.get_json(f"{levels_key(self.mip)}/{z}")
      if doc is None:
        raise FileNotFoundError(
          f"levels histogram missing for z={z}; run LuminanceLevelsTask first"
        )
      low, high = compute_stretch_bounds(
        np.asarray(doc["levels"]), self.clip_fraction
      )
      width = int(doc.get("bin_width", 1))
      low, high = low * width, high * width
      plane = img[:, :, dz]
      stretched = (plane - low) / max(high - low, 1) * (
        self.maxval - self.minval
      ) + self.minval
      img[:, :, dz] = stretched

    img = np.clip(np.round(img), self.minval, self.maxval).astype(dest.dtype)
    dest.upload(bounds.translate(self.translate), img)


class CLAHETask(RegisteredTask):
  """Per-z-slice contrast-limited adaptive histogram equalization
  (reference :164-209; OpenCV backend with single-threading, since
  parallelism comes from the task grid)."""

  def __init__(
    self,
    src_path: str,
    dest_path: str,
    shape: Sequence[int],
    offset: Sequence[int],
    mip: int = 0,
    clip_limit: float = 40.0,
    tile_grid_size=8,
    fill_missing: bool = False,
  ):
    self.src_path = src_path
    self.dest_path = dest_path
    self.shape = Vec(*shape)
    self.offset = Vec(*offset)
    self.mip = int(mip)
    self.clip_limit = float(clip_limit)
    # int or (gx, gy) pair (reference --tile-grid-size is a Tuple2)
    if isinstance(tile_grid_size, (list, tuple)):
      self.tile_grid_size = [int(v) for v in tile_grid_size]
    else:
      self.tile_grid_size = [int(tile_grid_size)] * 2
    self.fill_missing = fill_missing

  def execute(self):
    import cv2

    cv2.setNumThreads(0)  # the grid parallelizes; cv2 threads would fight it
    src = Volume(self.src_path, mip=self.mip, fill_missing=self.fill_missing,
                 bounded=False)
    dest = Volume(self.dest_path, mip=self.mip)
    core = Bbox.intersection(
      Bbox(self.offset, self.offset + self.shape), src.bounds
    )
    if core.empty():
      return
    # overlap-pad x/y by one CLAHE tile so tile boundaries don't show at
    # task seams (reference :192-197)
    tile = np.asarray(core.size3()[:2]) // np.asarray(self.tile_grid_size)
    pad = Vec(int(tile[0]), int(tile[1]), 0)
    cutout = Bbox.intersection(
      Bbox(core.minpt - pad, core.maxpt + pad), src.bounds
    )
    img = src.download(cutout)[..., 0]

    clahe = cv2.createCLAHE(
      clipLimit=self.clip_limit,
      tileGridSize=tuple(self.tile_grid_size),
    )
    out = np.empty_like(img)
    for dz in range(img.shape[2]):
      out[:, :, dz] = clahe.apply(img[:, :, dz])

    sl = tuple(
      slice(int(a), int(b))
      for a, b in zip(core.minpt - cutout.minpt, core.maxpt - cutout.minpt)
    )
    dest.upload(core, out[sl].astype(dest.dtype))
