"""Multi-resolution mesh merge tasks (stage 2, LOD format).

Reference parity: /root/reference/igneous/tasks/mesh/multires.py
  MultiResUnshardedMeshMergeTask (:44-81)
  MultiResShardedMeshMergeTask (:206-260)
  MultiResShardedFromUnshardedMeshMergeTask (:262-306)

Fragment payloads are draco bitstreams from the built-in codec
(igneous_tpu.draco; override via mesh_io.register_draco_codec), in
stored-lattice space per fragment cell; everything structural — LOD
pyramid, octree fragments, z-ordering, multilod manifests, shard
synthesis with fragment-before-manifest layout — is format-complete.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Optional, Sequence

import numpy as np

from ..lib import Bbox, Vec
from ..queues.registry import RegisteredTask
from ..storage import CloudFiles
from ..volume import Volume
from ..mesh_io import FragMap, Mesh, decode_mesh
from ..mesh_multires import multires_info, process_mesh
from ..spatial_index import SpatialIndex
from .mesh import mesh_dir_for


def legacy_label_fragments(cf, src_dir: str, prefix: str = "") -> dict:
  """{label: [fragment filenames]} under ``src_dir`` discovered from the
  ``<label>:0:<bbox>`` fragment files themselves (the reference's
  get_mesh_filenames_subset, multires.py:367-383 — no manifest pass is
  required between forge and a multires merge) plus any legacy
  ``<label>:0`` manifests."""
  out = {}
  for key in cf.list(f"{src_dir}/{prefix}"):
    name = key.split("/")[-1]
    parts = name.split(":")
    if len(parts) == 3 and parts[1] == "0":
      out.setdefault(int(parts[0]), set()).add(name)
    elif len(parts) == 2 and parts[1] == "0":
      out.setdefault(int(parts[0]), set())
  return {label: sorted(names) for label, names in out.items()}


def legacy_manifest_labels(cf, src_dir: str, prefix: str = "") -> list:
  """Labels present as legacy manifests OR raw fragment files."""
  return sorted(legacy_label_fragments(cf, src_dir, prefix).keys())


def _fetch_legacy_label_mesh(
  cf, src_dir: str, label: int, fragments=None,
) -> Optional[Mesh]:
  """Assemble one label's mesh from its fragment files (listed directly
  and/or via a legacy ``<label>:0`` manifest)."""
  names = set(fragments or [])
  manifest = cf.get_json(f"{src_dir}/{label}:0")
  if manifest is not None:
    names.update(manifest.get("fragments", []))
  pieces = []
  for frag in sorted(names):
    data = cf.get(f"{src_dir}/{frag}")
    if data is not None:
      pieces.append(Mesh.from_precomputed(data))
  if not pieces:
    return None
  return Mesh.concatenate(*pieces).consolidate()


def _map_labels(fn, labels, parallel: int):
  """Per-label merge work threaded across cores: every stage is numpy or
  a GIL-releasing ctypes call (the QEM collapse inside process_mesh), and
  results are keyed by label, so outputs are order-independent and
  byte-identical to the serial path."""
  labels = list(labels)
  if int(parallel) > 1 and len(labels) > 1:
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=int(parallel)) as ex:
      return list(ex.map(fn, labels))
  return [fn(l) for l in labels]


def _multires_process_kw(vol, info, min_chunk_size):
  """Per-label process_mesh kwargs derived from the multires info:
  quantization bits from the info file; min_chunk_size (voxels) scaled to
  physical units by the info's mip resolution (reference multires.py
  divides vertices by resolution instead; same cap either way)."""
  kw = {"quantization_bits": int(info.get("vertex_quantization_bits", 16))}
  if min_chunk_size is not None:
    import numpy as _np

    res = _np.asarray(vol.meta.resolution(int(info.get("mip", 0))))
    kw["min_chunk_size"] = (_np.asarray(min_chunk_size) * res).tolist()
  return kw


class MultiResUnshardedMeshMergeTask(RegisteredTask):
  """Legacy fragments → unsharded multires: per label ``<label>.index``
  manifest + ``<label>`` fragment file (reference :44-81)."""

  def __init__(
    self,
    cloudpath: str,
    prefix: str,
    src_mesh_dir: Optional[str] = None,
    mesh_dir: Optional[str] = None,
    num_lods: int = 2,
    encoding: str = "draco",
    parallel: int = 1,
    min_chunk_size=None,
    draco_compression_level: int = 7,
  ):
    self.cloudpath = cloudpath
    self.prefix = str(prefix)
    self.src_mesh_dir = src_mesh_dir
    self.mesh_dir = mesh_dir
    self.num_lods = int(num_lods)
    self.encoding = encoding
    self.parallel = int(parallel)
    self.min_chunk_size = (
      [int(v) for v in min_chunk_size] if min_chunk_size else None
    )
    # interface parity: this build's draco encoder is fixed
    # sequential-method, so the level knob is recorded but inert
    self.draco_compression_level = int(draco_compression_level)

  def execute(self):
    vol = Volume(self.cloudpath)
    src_dir = self.src_mesh_dir or mesh_dir_for(vol, None)
    out_dir = self.mesh_dir or f"{src_dir}_multires"
    cf = CloudFiles(vol.cloudpath)
    info = cf.get_json(f"{out_dir}/info") or {}
    pkw = _multires_process_kw(vol, info, self.min_chunk_size)

    per_label = legacy_label_fragments(cf, src_dir, self.prefix)

    def one(label):
      # writes happen inside the worker: per-label outputs are
      # independent files, so streaming keeps peak memory at
      # O(parallel labels) instead of O(all labels)
      mesh = _fetch_legacy_label_mesh(
        cf, src_dir, label, fragments=per_label.get(label)
      )
      if mesh is None or len(mesh.faces) == 0:
        return None
      manifest, frags = process_mesh(
        mesh, num_lods=self.num_lods, encoding=self.encoding, **pkw
      )
      cf.put(f"{out_dir}/{label}.index", manifest)
      cf.put(f"{out_dir}/{label}", frags)
      return None

    _map_labels(one, sorted(per_label.keys()), self.parallel)


class MultiResShardedMeshMergeTask(RegisteredTask):
  """Sharded stage-1 ``.frags`` → one multires shard file
  (reference :206-260): fetch each label's fragments via the spatial
  index, fuse, build the LOD octree, synthesize the shard with fragment
  data immediately preceding each manifest."""

  def __init__(
    self,
    cloudpath: str,
    shard_no: int,
    mesh_dir: Optional[str] = None,
    num_lods: int = 2,
    encoding: str = "draco",
    parallel: int = 1,
    min_chunk_size=None,
    draco_compression_level: int = 7,
  ):
    self.cloudpath = cloudpath
    self.shard_no = int(shard_no)
    self.mesh_dir = mesh_dir
    self.num_lods = int(num_lods)
    self.encoding = encoding
    self.parallel = int(parallel)
    self.min_chunk_size = (
      [int(v) for v in min_chunk_size] if min_chunk_size else None
    )
    self.draco_compression_level = int(draco_compression_level)

  def execute(self):
    from ..sharding import ShardingSpecification

    vol = Volume(self.cloudpath)
    mdir = mesh_dir_for(vol, self.mesh_dir)
    cf = CloudFiles(vol.cloudpath)
    info = cf.get_json(f"{mdir}/info") or {}
    spec = ShardingSpecification.from_dict(info["sharding"])
    pkw = _multires_process_kw(vol, info, self.min_chunk_size)

    si = SpatialIndex(cf, mdir)
    locations = si.file_locations_per_label()
    labels = np.array(sorted(locations.keys()), dtype=np.uint64)
    if len(labels) == 0:
      return
    mine = labels[spec.shard_number(labels) == self.shard_no]
    if len(mine) == 0:
      return

    needed = sorted({f for lbl in mine for f in locations[int(lbl)]})
    # concurrent container fetches (reference: ThreadPoolExecutor in
    # collect_mesh_fragments, multires.py:459); order preserved
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=8) as ex:
      datas = list(ex.map(
        lambda k: cf.get(k.replace(".spatial", ".frags")), needed
      ))
    fragmaps = [FragMap.frombytes(d) for d in datas if d is not None]

    def one(label):
      pieces = []
      for fm in fragmaps:
        blob = fm.get(label)
        if blob is not None:
          pieces.append(Mesh.from_precomputed(blob))
      if not pieces:
        return None
      mesh = Mesh.concatenate(*pieces).consolidate()
      if len(mesh.faces) == 0:
        return None
      manifest, frags = process_mesh(
        mesh, num_lods=self.num_lods, encoding=self.encoding, **pkw
      )
      return int(label), manifest, frags

    manifests = {}
    preambles = {}
    for item in _map_labels(one, mine.tolist(), self.parallel):
      if item is None:
        continue
      label, manifest, frags = item
      manifests[label] = manifest
      preambles[label] = frags

    if manifests:
      files = spec.synthesize_shard_files(manifests, preambles=preambles)
      for filename, data in files.items():
        cf.put(f"{mdir}/{filename}", data, compress=None)


class MultiResShardedFromUnshardedMeshMergeTask(RegisteredTask):
  """Legacy unsharded meshes → one multires shard (reference :262-306).
  ``dest_cloudpath`` writes the shard into a different volume (the
  `mesh xfer --sharded` conversion path, reference cli.py:1001-1007)."""

  def __init__(
    self,
    cloudpath: str,
    shard_no: int,
    src_mesh_dir: str,
    mesh_dir: str,
    num_lods: int = 2,
    encoding: str = "draco",
    parallel: int = 1,
    min_chunk_size=None,
    draco_compression_level: int = 7,
    dest_cloudpath: Optional[str] = None,
  ):
    self.cloudpath = cloudpath
    self.shard_no = int(shard_no)
    self.src_mesh_dir = src_mesh_dir
    self.mesh_dir = mesh_dir
    self.num_lods = int(num_lods)
    self.encoding = encoding
    self.parallel = int(parallel)
    self.min_chunk_size = (
      [int(v) for v in min_chunk_size] if min_chunk_size else None
    )
    self.draco_compression_level = int(draco_compression_level)
    self.dest_cloudpath = dest_cloudpath

  def execute(self):
    from ..sharding import ShardingSpecification

    vol = Volume(self.cloudpath)
    cf = CloudFiles(vol.cloudpath)
    out_cf = CloudFiles(self.dest_cloudpath or self.cloudpath)
    info = out_cf.get_json(f"{self.mesh_dir}/info") or {}
    spec = ShardingSpecification.from_dict(info["sharding"])
    pkw = _multires_process_kw(vol, info, self.min_chunk_size)

    per_label = legacy_label_fragments(cf, self.src_mesh_dir)
    labels = np.array(sorted(per_label.keys()), dtype=np.uint64)
    if len(labels) == 0:
      return
    mine = labels[spec.shard_number(labels) == self.shard_no]

    def one(label):
      mesh = _fetch_legacy_label_mesh(
        cf, self.src_mesh_dir, label, fragments=per_label.get(int(label))
      )
      if mesh is None or len(mesh.faces) == 0:
        return None
      manifest, frags = process_mesh(
        mesh, num_lods=self.num_lods, encoding=self.encoding, **pkw
      )
      return int(label), manifest, frags

    manifests = {}
    preambles = {}
    for item in _map_labels(one, mine.tolist(), self.parallel):
      if item is None:
        continue
      label, manifest, frags = item
      manifests[label] = manifest
      preambles[label] = frags

    if manifests:
      files = spec.synthesize_shard_files(manifests, preambles=preambles)
      for filename, data in files.items():
        out_cf.put(f"{self.mesh_dir}/{filename}", data, compress=None)
