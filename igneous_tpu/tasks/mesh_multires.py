"""Multi-resolution mesh merge tasks (stage 2, LOD format).

Reference parity: /root/reference/igneous/tasks/mesh/multires.py
  MultiResUnshardedMeshMergeTask (:44-81)
  MultiResShardedMeshMergeTask (:206-260)
  MultiResShardedFromUnshardedMeshMergeTask (:262-306)

Fragment payloads are draco bitstreams from the built-in codec
(igneous_tpu.draco; override via mesh_io.register_draco_codec), in
stored-lattice space per fragment cell; everything structural — LOD
pyramid, octree fragments, z-ordering, multilod manifests, shard
synthesis with fragment-before-manifest layout — is format-complete.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Optional, Sequence

import numpy as np

from ..lib import Bbox, Vec
from ..queues.registry import RegisteredTask
from ..storage import CloudFiles
from ..volume import Volume
from ..mesh_io import FragMap, Mesh, decode_mesh
from ..mesh_multires import multires_info, process_mesh
from ..spatial_index import SpatialIndex
from .mesh import mesh_dir_for


def legacy_manifest_labels(cf, src_dir: str, prefix: str = "") -> list:
  """Labels present as legacy ``<label>:0`` manifests under ``src_dir``."""
  labels = set()
  for key in cf.list(f"{src_dir}/{prefix}"):
    parts = key.split("/")[-1].split(":")
    if len(parts) == 2 and parts[1] == "0":
      labels.add(int(parts[0]))
  return sorted(labels)


def _fetch_legacy_label_mesh(cf, src_dir: str, label: int) -> Optional[Mesh]:
  """Assemble one label's mesh from legacy manifest + fragment files."""
  manifest = cf.get_json(f"{src_dir}/{label}:0")
  if manifest is None:
    return None
  pieces = []
  for frag in manifest.get("fragments", []):
    data = cf.get(f"{src_dir}/{frag}")
    if data is not None:
      pieces.append(Mesh.from_precomputed(data))
  if not pieces:
    return None
  return Mesh.concatenate(*pieces).consolidate()


def _map_labels(fn, labels, parallel: int):
  """Per-label merge work threaded across cores: every stage is numpy or
  a GIL-releasing ctypes call (the QEM collapse inside process_mesh), and
  results are keyed by label, so outputs are order-independent and
  byte-identical to the serial path."""
  labels = list(labels)
  if int(parallel) > 1 and len(labels) > 1:
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=int(parallel)) as ex:
      return list(ex.map(fn, labels))
  return [fn(l) for l in labels]


class MultiResUnshardedMeshMergeTask(RegisteredTask):
  """Legacy fragments → unsharded multires: per label ``<label>.index``
  manifest + ``<label>`` fragment file (reference :44-81)."""

  def __init__(
    self,
    cloudpath: str,
    prefix: str,
    src_mesh_dir: Optional[str] = None,
    mesh_dir: Optional[str] = None,
    num_lods: int = 2,
    encoding: str = "draco",
    parallel: int = 1,
  ):
    self.cloudpath = cloudpath
    self.prefix = str(prefix)
    self.src_mesh_dir = src_mesh_dir
    self.mesh_dir = mesh_dir
    self.num_lods = int(num_lods)
    self.encoding = encoding
    self.parallel = int(parallel)

  def execute(self):
    vol = Volume(self.cloudpath)
    src_dir = self.src_mesh_dir or mesh_dir_for(vol, None)
    out_dir = self.mesh_dir or f"{src_dir}_multires"
    cf = CloudFiles(vol.cloudpath)

    def one(label):
      # writes happen inside the worker: per-label outputs are
      # independent files, so streaming keeps peak memory at
      # O(parallel labels) instead of O(all labels)
      mesh = _fetch_legacy_label_mesh(cf, src_dir, label)
      if mesh is None or len(mesh.faces) == 0:
        return None
      manifest, frags = process_mesh(
        mesh, num_lods=self.num_lods, encoding=self.encoding
      )
      cf.put(f"{out_dir}/{label}.index", manifest)
      cf.put(f"{out_dir}/{label}", frags)
      return None

    _map_labels(
      one, legacy_manifest_labels(cf, src_dir, self.prefix), self.parallel
    )


class MultiResShardedMeshMergeTask(RegisteredTask):
  """Sharded stage-1 ``.frags`` → one multires shard file
  (reference :206-260): fetch each label's fragments via the spatial
  index, fuse, build the LOD octree, synthesize the shard with fragment
  data immediately preceding each manifest."""

  def __init__(
    self,
    cloudpath: str,
    shard_no: int,
    mesh_dir: Optional[str] = None,
    num_lods: int = 2,
    encoding: str = "draco",
    parallel: int = 1,
  ):
    self.cloudpath = cloudpath
    self.shard_no = int(shard_no)
    self.mesh_dir = mesh_dir
    self.num_lods = int(num_lods)
    self.encoding = encoding
    self.parallel = int(parallel)

  def execute(self):
    from ..sharding import ShardingSpecification

    vol = Volume(self.cloudpath)
    mdir = mesh_dir_for(vol, self.mesh_dir)
    cf = CloudFiles(vol.cloudpath)
    info = cf.get_json(f"{mdir}/info") or {}
    spec = ShardingSpecification.from_dict(info["sharding"])

    si = SpatialIndex(cf, mdir)
    locations = si.file_locations_per_label()
    labels = np.array(sorted(locations.keys()), dtype=np.uint64)
    if len(labels) == 0:
      return
    mine = labels[spec.shard_number(labels) == self.shard_no]
    if len(mine) == 0:
      return

    needed = sorted({f for lbl in mine for f in locations[int(lbl)]})
    # concurrent container fetches (reference: ThreadPoolExecutor in
    # collect_mesh_fragments, multires.py:459); order preserved
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=8) as ex:
      datas = list(ex.map(
        lambda k: cf.get(k.replace(".spatial", ".frags")), needed
      ))
    fragmaps = [FragMap.frombytes(d) for d in datas if d is not None]

    def one(label):
      pieces = []
      for fm in fragmaps:
        blob = fm.get(label)
        if blob is not None:
          pieces.append(Mesh.from_precomputed(blob))
      if not pieces:
        return None
      mesh = Mesh.concatenate(*pieces).consolidate()
      if len(mesh.faces) == 0:
        return None
      manifest, frags = process_mesh(
        mesh, num_lods=self.num_lods, encoding=self.encoding
      )
      return int(label), manifest, frags

    manifests = {}
    preambles = {}
    for item in _map_labels(one, mine.tolist(), self.parallel):
      if item is None:
        continue
      label, manifest, frags = item
      manifests[label] = manifest
      preambles[label] = frags

    if manifests:
      files = spec.synthesize_shard_files(manifests, preambles=preambles)
      for filename, data in files.items():
        cf.put(f"{mdir}/{filename}", data, compress=None)


class MultiResShardedFromUnshardedMeshMergeTask(RegisteredTask):
  """Legacy unsharded meshes → one multires shard (reference :262-306)."""

  def __init__(
    self,
    cloudpath: str,
    shard_no: int,
    src_mesh_dir: str,
    mesh_dir: str,
    num_lods: int = 2,
    encoding: str = "draco",
    parallel: int = 1,
  ):
    self.cloudpath = cloudpath
    self.shard_no = int(shard_no)
    self.src_mesh_dir = src_mesh_dir
    self.mesh_dir = mesh_dir
    self.num_lods = int(num_lods)
    self.encoding = encoding
    self.parallel = int(parallel)

  def execute(self):
    from ..sharding import ShardingSpecification

    vol = Volume(self.cloudpath)
    cf = CloudFiles(vol.cloudpath)
    info = cf.get_json(f"{self.mesh_dir}/info") or {}
    spec = ShardingSpecification.from_dict(info["sharding"])

    labels = np.array(
      legacy_manifest_labels(cf, self.src_mesh_dir), dtype=np.uint64
    )
    if len(labels) == 0:
      return
    mine = labels[spec.shard_number(labels) == self.shard_no]

    def one(label):
      mesh = _fetch_legacy_label_mesh(cf, self.src_mesh_dir, label)
      if mesh is None or len(mesh.faces) == 0:
        return None
      manifest, frags = process_mesh(
        mesh, num_lods=self.num_lods, encoding=self.encoding
      )
      return int(label), manifest, frags

    manifests = {}
    preambles = {}
    for item in _map_labels(one, mine.tolist(), self.parallel):
      if item is None:
        continue
      label, manifest, frags = item
      manifests[label] = manifest
      preambles[label] = frags

    if manifests:
      files = spec.synthesize_shard_files(manifests, preambles=preambles)
      for filename, data in files.items():
        cf.put(f"{self.mesh_dir}/{filename}", data, compress=None)
