"""Shared HTTP transport for the cloud object-store clients.

stdlib-only (urllib) with the retry/backoff discipline both real object
stores require: exponential backoff + jitter on connection errors, 429,
and 5xx — the same policy cloud-files applies for the reference stack
(SURVEY.md §2.2). gs:// (storage_gcs.py), s3:// (storage_s3.py), and
the PCG client (graphene_http.py) ride this one transport so the policy
can't drift between them; the schedule itself lives in retry.RetryPolicy
(base/cap/jitter/budget, env-tunable) and every retry bumps the
``retries.storage_http`` telemetry counter.
"""

from __future__ import annotations

import dataclasses
import urllib.error
import urllib.request
from typing import Dict, Optional, Tuple

from .retry import RETRYABLE_STATUS, RetryPolicy, default_policy

MAX_RETRIES = 6  # legacy alias; the live value is RetryPolicy.attempts


class HttpError(Exception):
  def __init__(self, status: int, url: str, body: bytes = b""):
    self.status = status
    self.url = url
    self.body = body
    super().__init__(f"HTTP {status} for {url}: {body[:200]!r}")


def request(
  method: str,
  url: str,
  headers: Optional[Dict[str, str]] = None,
  data: Optional[bytes] = None,
  timeout: float = 60.0,
  retries: Optional[int] = None,
  allow_status: Tuple[int, ...] = (),
  policy: Optional[RetryPolicy] = None,
) -> Tuple[int, Dict[str, str], bytes]:
  """One HTTP exchange with retry/backoff. Returns (status, headers, body).

  404/416 return normally (callers map them to None); ``allow_status``
  passes additional statuses through (GCS resumable-chunk PUTs expect
  308 "resume incomplete" — but only that caller: a get() must never
  hand a redirect body back as object content); other non-retryable
  statuses raise HttpError; retryable statuses and connection errors
  retry per ``policy`` (default: retry.default_policy(), env-tunable
  exponential backoff + full jitter + total-sleep budget), then raise.
  ``retries`` overrides the policy's attempt count (legacy knob)."""
  pol = policy or default_policy()
  if retries is not None and retries != pol.attempts:
    pol = dataclasses.replace(pol, attempts=retries)
  retry_iter = pol.retries("storage_http")
  last_exc: Optional[Exception] = None
  while True:
    req = urllib.request.Request(
      url, data=data, method=method, headers=dict(headers or {})
    )
    try:
      with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
      body = e.read()
      # 404/416: caller maps to None/empty (urllib raises on non-2xx)
      if e.code in (404, 416) or e.code in allow_status:
        return e.code, dict(e.headers or {}), body
      if e.code not in RETRYABLE_STATUS:
        raise HttpError(e.code, url, body) from None
      last_exc = HttpError(e.code, url, body)
    except (urllib.error.URLError, ConnectionError, TimeoutError) as e:
      last_exc = e
    if next(retry_iter, None) is None:  # attempts or sleep budget spent
      raise last_exc


def quote_path(segment: str) -> str:
  import urllib.parse

  return urllib.parse.quote(segment, safe="")
