"""Shared HTTP transport for the cloud object-store clients.

stdlib-only (urllib) with the retry/backoff discipline both real object
stores require: exponential backoff + jitter on connection errors, 429,
and 5xx — the same policy cloud-files applies for the reference stack
(SURVEY.md §2.2). gs:// (storage_gcs.py) and s3:// (storage_s3.py) ride
this one transport so the policy can't drift between them.
"""

from __future__ import annotations

import random
import time
import urllib.error
import urllib.request
from typing import Dict, Optional, Tuple

RETRYABLE_STATUS = (408, 429, 500, 502, 503, 504)
MAX_RETRIES = 6
BACKOFF_BASE_S = 0.25
BACKOFF_CAP_S = 30.0


class HttpError(Exception):
  def __init__(self, status: int, url: str, body: bytes = b""):
    self.status = status
    self.url = url
    self.body = body
    super().__init__(f"HTTP {status} for {url}: {body[:200]!r}")


def request(
  method: str,
  url: str,
  headers: Optional[Dict[str, str]] = None,
  data: Optional[bytes] = None,
  timeout: float = 60.0,
  retries: int = MAX_RETRIES,
  allow_status: Tuple[int, ...] = (),
) -> Tuple[int, Dict[str, str], bytes]:
  """One HTTP exchange with retry/backoff. Returns (status, headers, body).

  404/416 return normally (callers map them to None); ``allow_status``
  passes additional statuses through (GCS resumable-chunk PUTs expect
  308 "resume incomplete" — but only that caller: a get() must never
  hand a redirect body back as object content); other non-retryable
  statuses raise HttpError; retryable statuses and connection errors
  retry with exponential backoff + full jitter, then raise."""
  last_exc: Optional[Exception] = None
  for attempt in range(retries):
    req = urllib.request.Request(
      url, data=data, method=method, headers=dict(headers or {})
    )
    try:
      with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
      body = e.read()
      # 404/416: caller maps to None/empty (urllib raises on non-2xx)
      if e.code in (404, 416) or e.code in allow_status:
        return e.code, dict(e.headers or {}), body
      if e.code in RETRYABLE_STATUS and attempt + 1 < retries:
        last_exc = HttpError(e.code, url, body)
      else:
        raise HttpError(e.code, url, body) from None
    except (urllib.error.URLError, ConnectionError, TimeoutError) as e:
      if attempt + 1 >= retries:
        raise
      last_exc = e
    delay = min(BACKOFF_CAP_S, BACKOFF_BASE_S * (2**attempt))
    time.sleep(random.random() * delay)
  raise last_exc  # pragma: no cover - loop always returns or raises


def quote_path(segment: str) -> str:
  import urllib.parse

  return urllib.parse.quote(segment, safe="")
