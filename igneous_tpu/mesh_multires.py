"""Multi-resolution (LOD) mesh format: octree chunking + manifests.

Reference parity: /root/reference/igneous/tasks/mesh/multires.py
(process_mesh :83-178, create_octree_level_from_mesh + z-order sort
:515-586, labels_for_shard :484-508) and igneous/tasks/mesh/draco.py
(quantization settings solver :7-59).

Produces the Neuroglancer ``neuroglancer_multilod_draco`` structures:
per-label manifest (chunk grid, lod scales, fragment positions/sizes) and
per-LOD octree fragments. Fragment payloads are draco bitstreams from the
built-in codec (igneous_tpu.draco) by default, quantized per fragment so
the lattice spans the fragment's octree cell — the contract Neuroglancer's
multires renderer consumes (reference multires.py:144-177).
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .lib import Bbox
from .mesh_io import Mesh, encode_mesh, simplify
from .sharding import compressed_morton_code


def to_stored_lattice(
  vertices: np.ndarray,
  cell_origin: np.ndarray,
  cell_size: np.ndarray,
  vertex_quantization_bits: int,
) -> np.ndarray:
  """Transform model-space vertices into Neuroglancer's stored-model
  lattice for one multires fragment: per-axis, the fragment's octree cell
  maps onto [0, 2**vertex_quantization_bits]. This is the coordinate
  system the multires renderer consumes (reference equivalent:
  to_stored_model_space before DracoPy.encode, multires.py:144-177)."""
  scale = float(1 << vertex_quantization_bits) / np.asarray(cell_size, np.float64)
  return (np.asarray(vertices, np.float64) - cell_origin) * scale


def fragment_draco_settings(vertex_quantization_bits: int = 16) -> dict:
  """Draco encode settings for a stored-lattice fragment: one more bit
  than the lattice and range 2**(bits+1)-1 makes the draco bin size
  exactly 1 lattice unit, so lattice integers 0..2**bits round-trip
  bit-exactly and adjacent fragments stitch on shared wall points (fresh
  derivation of the reference draco.py:7-59 alignment contract — with the
  lattice transform applied first, the general solver reduces to this
  closed form)."""
  bits = vertex_quantization_bits + 1
  if bits > 30:
    raise ValueError(f"vertex_quantization_bits too large: {bits - 1}")
  return {
    "quantization_bits": bits,
    "quantization_origin": (0.0, 0.0, 0.0),
    "quantization_range": float((1 << bits) - 1),
  }


def _zorder(positions: np.ndarray) -> np.ndarray:
  """Sort order of (n, 3) grid positions by compressed morton code
  (reference multires.py:515-529)."""
  if len(positions) == 0:
    return np.zeros(0, dtype=np.int64)
  gs = positions.max(axis=0) + 1
  codes = [int(compressed_morton_code(p, gs)) for p in positions]
  return np.argsort(np.asarray(codes), kind="stable")


def clip_polygons(
  verts: np.ndarray, counts: np.ndarray, axis: int, sign: float, bound: float
) -> Tuple[np.ndarray, np.ndarray]:
  """Sutherland-Hodgman clip of padded polygons against one axis plane.

  verts (P, K, 3) float64 with per-polygon vertex counts; keeps the
  half-space ``sign * (x[axis] - bound) <= 0``. Vectorized over polygons —
  the per-edge loop runs K (≤ 9) times regardless of P.
  """
  P, K, _ = verts.shape
  out = np.zeros((P, K + 1, 3), dtype=np.float64)
  outc = np.zeros(P, dtype=np.int64)
  d = sign * (verts[:, :, axis] - bound)  # signed distance, (P, K)
  inside = d <= 1e-9
  rows = np.arange(P)
  for k in range(K):
    valid = k < counts
    j = np.where(k + 1 < counts, k + 1, 0)
    vi, vj = verts[rows, k], verts[rows, j]
    di, dj = d[rows, k], d[rows, j]
    ini, inj = inside[rows, k], inside[rows, j]
    # emit current vertex if inside
    emit = valid & ini
    out[rows[emit], outc[emit]] = vi[emit]
    outc[emit] += 1
    # emit intersection if the edge crosses the plane; clamp t — the
    # inside tolerance admits points marginally past the plane, and an
    # unclamped near-parallel edge would extrapolate a spike far outside
    cross = valid & (ini != inj)
    if cross.any():
      t = np.clip(di[cross] / (di[cross] - dj[cross]), 0.0, 1.0)
      pt = vi[cross] + t[:, None] * (vj[cross] - vi[cross])
      pt[:, axis] = bound  # exact landing on the wall (lattice-stitchable)
      out[rows[cross], outc[cross]] = pt
      outc[cross] += 1
  return out, outc


def _triangulate_fans(verts: np.ndarray, counts: np.ndarray) -> np.ndarray:
  """Fan-triangulate padded convex polygons → (T, 3, 3) triangles."""
  tris = []
  for c in range(3, int(counts.max()) + 1 if len(counts) else 3):
    sel = counts >= c
    if not sel.any():
      continue
    v = verts[sel]
    tris.append(np.stack([v[:, 0], v[:, c - 2], v[:, c - 1]], axis=1))
  if not tris:
    return np.zeros((0, 3, 3), dtype=np.float64)
  return np.concatenate(tris, axis=0)


def clip_triangles_to_box(
  tri: np.ndarray, lo: np.ndarray, hi: np.ndarray
) -> np.ndarray:
  """Clip triangles (T, 3, 3) to an axis box; returns retriangulated
  (T', 3, 3). Capability equivalent of zmesh.chunk_mesh (reference
  multires.py:542-552): fragment geometry ends exactly at cell walls so
  per-cell quantization never clamps, and adjacent fragments stitch."""
  if len(tri) == 0:
    return np.zeros((0, 3, 3), dtype=np.float64)
  K = 3
  verts = np.zeros((len(tri), K, 3), dtype=np.float64)
  verts[:, :3] = tri
  counts = np.full(len(tri), 3, dtype=np.int64)
  for axis in range(3):
    for sign, bound in ((-1.0, float(lo[axis])), (1.0, float(hi[axis]))):
      verts, counts = clip_polygons(verts, counts, axis, sign, bound)
      keep = counts >= 3
      verts, counts = verts[keep], counts[keep]
      if len(verts) == 0:
        return np.zeros((0, 3, 3), dtype=np.float64)
  return _triangulate_fans(verts, counts)


def octree_fragments(
  mesh: Mesh, chunk_size: np.ndarray, grid_origin: np.ndarray
) -> Dict[Tuple[int, int, int], Mesh]:
  """Split a mesh into octree cells, retriangulating triangles at cell
  walls (reference: zmesh.chunk_mesh via retriangulate_mesh,
  multires.py:542-552). Triangles fully inside a cell pass through
  untouched; spanning triangles are clipped into every cell they touch so
  fragment geometry lies exactly within its cell — required for the
  per-cell draco quantization lattice."""
  if len(mesh.faces) == 0:
    return {}
  chunk_size = np.asarray(chunk_size, dtype=np.float64)
  grid_origin = np.asarray(grid_origin, dtype=np.float64)
  tri = mesh.vertices[mesh.faces.astype(np.int64)].astype(np.float64)
  eps = 1e-9
  clo = np.floor((tri.min(axis=1) - grid_origin) / chunk_size - eps)
  chi = np.floor((tri.max(axis=1) - grid_origin) / chunk_size + eps)
  clo = np.maximum(clo.astype(np.int64), 0)
  chi = np.maximum(chi.astype(np.int64), clo)
  # a triangle flat along an axis and sitting exactly on a cell wall would
  # satisfy the inclusive clip of BOTH adjacent cells and be emitted twice;
  # pin such axes to the centroid's cell (the old centroid convention)
  flat = (tri.max(axis=1) - tri.min(axis=1)) <= eps * np.maximum(chunk_size, 1)
  if flat.any():
    cen = np.floor(
      (tri.mean(axis=1) - grid_origin) / chunk_size
    ).astype(np.int64)
    cen = np.maximum(cen, 0)
    clo = np.where(flat, cen, clo)
    chi = np.where(flat, cen, chi)

  spanning = (chi != clo).any(axis=1)
  out_tris: Dict[Tuple[int, int, int], List[np.ndarray]] = {}

  # bulk path: triangles entirely inside one cell
  interior = ~spanning
  if interior.any():
    keys, inverse = np.unique(clo[interior], axis=0, return_inverse=True)
    idx = np.flatnonzero(interior)
    for i, key in enumerate(keys):
      out_tris.setdefault(tuple(int(v) for v in key), []).append(
        tri[idx[inverse == i]]
      )

  # clip path: the minority of triangles that cross cell walls
  if spanning.any():
    span_cells: Dict[Tuple[int, int, int], List[int]] = {}
    for t in np.flatnonzero(spanning):
      for cx in range(clo[t, 0], chi[t, 0] + 1):
        for cy in range(clo[t, 1], chi[t, 1] + 1):
          for cz in range(clo[t, 2], chi[t, 2] + 1):
            span_cells.setdefault((cx, cy, cz), []).append(t)
    for key, tids in span_cells.items():
      lo = grid_origin + np.asarray(key, np.float64) * chunk_size
      hi = lo + chunk_size
      clipped = clip_triangles_to_box(tri[tids], lo, hi)
      if len(clipped):
        # drop zero-area slivers (e.g. an edge lying in this cell's wall
        # whose triangle body is in the neighbor): they render nothing
        # and would duplicate wall geometry across cells
        n = np.cross(
          clipped[:, 1] - clipped[:, 0], clipped[:, 2] - clipped[:, 0]
        )
        area2 = np.linalg.norm(n, axis=1)
        min_area2 = (1e-6 * float(chunk_size.max())) ** 2
        clipped = clipped[area2 > min_area2]
      if len(clipped):
        out_tris.setdefault(key, []).append(clipped)

  out: Dict[Tuple[int, int, int], Mesh] = {}
  for key, pieces in out_tris.items():
    tris = np.concatenate(pieces, axis=0)
    nverts = 3 * len(tris)
    sub = Mesh(
      tris.reshape(-1, 3).astype(np.float32),
      np.arange(nverts, dtype=np.uint32).reshape(-1, 3),
    ).consolidate()
    if len(sub.faces):
      out[key] = sub
  return out


def generate_lods(mesh: Mesh, num_lods: int, reduction: float = 4.0) -> List[Mesh]:
  """LOD pyramid: lod 0 is the full mesh; each level reduces ~4x
  (reference multires.py:308-359 via fqmr; here the clustering simplifier)."""
  lods = [mesh]
  for _ in range(1, num_lods):
    prev = lods[-1]
    if len(prev.faces) <= 16:
      lods.append(prev.clone())
      continue
    lods.append(simplify(prev, reduction_factor=reduction, max_error=None))
  return lods


def process_mesh(
  mesh: Mesh,
  num_lods: int = 2,
  chunk_size: Optional[Sequence[float]] = None,
  encoding: str = "draco",
  quantization_bits: int = 16,
  min_chunk_size: Optional[Sequence[float]] = None,
) -> Tuple[bytes, bytes]:
  """One label's mesh → (manifest bytes, concatenated fragment bytes).

  Neuroglancer multilod manifest layout (little endian):
    chunk_shape float32[3] | grid_origin float32[3] | num_lods uint32 |
    lod_scales float32[num_lods] | vertex_offsets float32[num_lods][3] |
    num_fragments_per_lod uint32[num_lods] |
    per lod: fragment_positions uint32[n][3], fragment_offsets uint32[n]
  Fragment data is concatenated lod 0 … lod n-1, z-order within each lod,
  in exactly the order fragment_offsets describes.
  """
  mesh = mesh.consolidate()
  if len(mesh.vertices) == 0:
    raise ValueError("empty mesh")
  mn = mesh.vertices.min(axis=0)
  mx = mesh.vertices.max(axis=0)
  if min_chunk_size is not None:
    # cap the LOD count so the finest fragment cell is at least
    # min_chunk_size (same units as the vertices) — reference
    # multires.py:102-104 derives max_lod from mesh_shape/min_chunk_size
    ext = np.maximum(np.asarray(mx - mn, dtype=np.float64), 1e-9)
    ratio = ext / np.maximum(np.asarray(min_chunk_size, np.float64), 1e-9)
    cap = 1 + max(int(np.floor(np.min(np.log2(np.maximum(ratio, 1.0))))), 0)
    num_lods = max(1, min(num_lods, cap))
  if chunk_size is None:
    # one chunk at the coarsest lod
    chunk_size = (mx - mn) / (2 ** (num_lods - 1)) + 1e-3
  chunk_size = np.asarray(chunk_size, dtype=np.float32)
  grid_origin = mn.astype(np.float32)

  lods = generate_lods(mesh, num_lods)

  frag_payloads: List[bytes] = []
  lod_positions: List[np.ndarray] = []
  lod_sizes: List[np.ndarray] = []
  for lod, lod_mesh in enumerate(lods):
    cell = chunk_size * (2**lod)
    frags = octree_fragments(lod_mesh, cell, grid_origin)
    positions = np.asarray(sorted(frags.keys()), dtype=np.int64).reshape(-1, 3)
    order = _zorder(positions)
    positions = positions[order]
    sizes = []
    for pos in positions:
      frag = frags[tuple(int(v) for v in pos)]
      kw = {}
      if encoding == "draco":
        # per-axis stored-lattice transform + 1-lattice-unit draco bins:
        # the renderer maps stored integers onto the fragment cell, so
        # anisotropic cells need per-axis normalization, not a scalar
        # range (reference multires.py:144-177 contract)
        frag = Mesh(
          to_stored_lattice(
            frag.vertices, grid_origin + pos * cell, cell, quantization_bits
          ).astype(np.float32),
          frag.faces,
        )
        kw = fragment_draco_settings(quantization_bits)
      payload = encode_mesh(frag, encoding, **kw)
      frag_payloads.append(payload)
      sizes.append(len(payload))
    lod_positions.append(positions.astype(np.uint32))
    lod_sizes.append(np.asarray(sizes, dtype=np.uint32))

  manifest = [
    chunk_size.astype("<f4").tobytes(),
    grid_origin.astype("<f4").tobytes(),
    struct.pack("<I", num_lods),
    np.asarray([2.0**lod for lod in range(num_lods)], "<f4").tobytes(),
    np.zeros((num_lods, 3), "<f4").tobytes(),  # vertex_offsets
    np.asarray([len(p) for p in lod_positions], "<u4").tobytes(),
  ]
  for positions, sizes in zip(lod_positions, lod_sizes):
    manifest.append(positions.astype("<u4").tobytes())
    manifest.append(sizes.astype("<u4").tobytes())

  return b"".join(manifest), b"".join(frag_payloads)


def multires_info(
  vertex_quantization_bits: int = 16,
  transform: Optional[Sequence[float]] = None,
  sharding: Optional[dict] = None,
  mip: int = 0,
) -> dict:
  """The multires mesh dir's info file
  (reference configure_multires_info, task_creation/mesh.py:437-479)."""
  info = {
    "@type": "neuroglancer_multilod_draco",
    "vertex_quantization_bits": int(vertex_quantization_bits),
    "transform": list(transform) if transform is not None
    else [1, 0, 0, 0, 0, 1, 0, 0, 0, 0, 1, 0],
    "lod_scale_multiplier": 1,
    "mip": int(mip),
  }
  if sharding is not None:
    info["sharding"] = sharding
  return info
