"""Multi-resolution (LOD) mesh format: octree chunking + manifests.

Reference parity: /root/reference/igneous/tasks/mesh/multires.py
(process_mesh :83-178, create_octree_level_from_mesh + z-order sort
:515-586, labels_for_shard :484-508) and igneous/tasks/mesh/draco.py
(quantization settings solver :7-59).

Produces the Neuroglancer ``neuroglancer_multilod_draco`` structures:
per-label manifest (chunk grid, lod scales, fragment positions/sizes) and
per-LOD octree fragments. Fragment payload encoding goes through the
pluggable draco hook (mesh_io.register_draco_codec) — no draco library
ships in this environment, so consumers must register one (tests register
a stand-in codec to exercise the full structure).
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .lib import Bbox
from .mesh_io import Mesh, encode_mesh, simplify
from .sharding import compressed_morton_code


def draco_quantization_settings(
  chunk_size: Sequence[float],
  grid_origin: Sequence[float],
  mesh_bbox: Bbox,
  quantization_bits: int = 16,
) -> dict:
  """Quantization origin/range/bits such that the draco grid aligns with
  chunk boundaries (fresh derivation of reference draco.py:7-59: the
  quantization step must evenly divide the chunk so fragment borders land
  on representable positions and adjacent fragments stitch exactly)."""
  chunk_size = np.asarray(chunk_size, dtype=np.float64)
  grid_origin = np.asarray(grid_origin, dtype=np.float64)
  span = np.asarray(mesh_bbox.maxpt, np.float64) - grid_origin
  n_chunks = np.maximum(np.ceil(span / chunk_size), 1)
  full_range = float(np.max(n_chunks * chunk_size))
  # steps per chunk must be a power of two so every chunk boundary is a
  # lattice point; choose the largest bits that keeps that true
  steps = (1 << quantization_bits) - 1
  steps_per_chunk = steps * chunk_size.max() / full_range
  bits_per_chunk = int(np.floor(np.log2(max(steps_per_chunk, 1))))
  return {
    "quantization_origin": [float(v) for v in grid_origin],
    "quantization_range": full_range,
    "quantization_bits": quantization_bits,
    "steps_per_chunk": 1 << max(bits_per_chunk, 0),
  }


def _zorder(positions: np.ndarray) -> np.ndarray:
  """Sort order of (n, 3) grid positions by compressed morton code
  (reference multires.py:515-529)."""
  if len(positions) == 0:
    return np.zeros(0, dtype=np.int64)
  gs = positions.max(axis=0) + 1
  codes = [int(compressed_morton_code(p, gs)) for p in positions]
  return np.argsort(np.asarray(codes), kind="stable")


def octree_fragments(
  mesh: Mesh, chunk_size: np.ndarray, grid_origin: np.ndarray
) -> Dict[Tuple[int, int, int], Mesh]:
  """Split a mesh into octree cells; each triangle goes to the cell
  containing its centroid (the reference retriangulates at cell walls via
  zmesh.chunk_mesh; centroid assignment keeps geometry identical while
  letting fragments slightly overhang their cells)."""
  if len(mesh.faces) == 0:
    return {}
  tri = mesh.vertices[mesh.faces.astype(np.int64)]  # (F, 3, 3)
  centroids = tri.mean(axis=1)
  cells = np.floor((centroids - grid_origin) / chunk_size).astype(np.int64)
  cells = np.maximum(cells, 0)
  out: Dict[Tuple[int, int, int], Mesh] = {}
  keys, inverse = np.unique(cells, axis=0, return_inverse=True)
  for i, key in enumerate(keys):
    faces = mesh.faces[inverse == i]
    sub = Mesh(mesh.vertices, faces).consolidate()
    out[tuple(int(v) for v in key)] = sub
  return out


def generate_lods(mesh: Mesh, num_lods: int, reduction: float = 4.0) -> List[Mesh]:
  """LOD pyramid: lod 0 is the full mesh; each level reduces ~4x
  (reference multires.py:308-359 via fqmr; here the clustering simplifier)."""
  lods = [mesh]
  for _ in range(1, num_lods):
    prev = lods[-1]
    if len(prev.faces) <= 16:
      lods.append(prev.clone())
      continue
    lods.append(simplify(prev, reduction_factor=reduction, max_error=None))
  return lods


def process_mesh(
  mesh: Mesh,
  num_lods: int = 2,
  chunk_size: Optional[Sequence[float]] = None,
  encoding: str = "draco",
  quantization_bits: int = 16,
) -> Tuple[bytes, bytes]:
  """One label's mesh → (manifest bytes, concatenated fragment bytes).

  Neuroglancer multilod manifest layout (little endian):
    chunk_shape float32[3] | grid_origin float32[3] | num_lods uint32 |
    lod_scales float32[num_lods] | vertex_offsets float32[num_lods][3] |
    num_fragments_per_lod uint32[num_lods] |
    per lod: fragment_positions uint32[n][3], fragment_offsets uint32[n]
  Fragment data is concatenated lod 0 … lod n-1, z-order within each lod,
  in exactly the order fragment_offsets describes.
  """
  mesh = mesh.consolidate()
  if len(mesh.vertices) == 0:
    raise ValueError("empty mesh")
  mn = mesh.vertices.min(axis=0)
  mx = mesh.vertices.max(axis=0)
  if chunk_size is None:
    # one chunk at the coarsest lod
    chunk_size = (mx - mn) / (2 ** (num_lods - 1)) + 1e-3
  chunk_size = np.asarray(chunk_size, dtype=np.float32)
  grid_origin = mn.astype(np.float32)

  lods = generate_lods(mesh, num_lods)

  frag_payloads: List[bytes] = []
  lod_positions: List[np.ndarray] = []
  lod_sizes: List[np.ndarray] = []
  for lod, lod_mesh in enumerate(lods):
    cell = chunk_size * (2**lod)
    frags = octree_fragments(lod_mesh, cell, grid_origin)
    positions = np.asarray(sorted(frags.keys()), dtype=np.int64).reshape(-1, 3)
    order = _zorder(positions)
    positions = positions[order]
    sizes = []
    for pos in positions:
      payload = encode_mesh(frags[tuple(int(v) for v in pos)], encoding)
      frag_payloads.append(payload)
      sizes.append(len(payload))
    lod_positions.append(positions.astype(np.uint32))
    lod_sizes.append(np.asarray(sizes, dtype=np.uint32))

  manifest = [
    chunk_size.astype("<f4").tobytes(),
    grid_origin.astype("<f4").tobytes(),
    struct.pack("<I", num_lods),
    np.asarray([2.0**lod for lod in range(num_lods)], "<f4").tobytes(),
    np.zeros((num_lods, 3), "<f4").tobytes(),  # vertex_offsets
    np.asarray([len(p) for p in lod_positions], "<u4").tobytes(),
  ]
  for positions, sizes in zip(lod_positions, lod_sizes):
    manifest.append(positions.astype("<u4").tobytes())
    manifest.append(sizes.astype("<u4").tobytes())

  return b"".join(manifest), b"".join(frag_payloads)


def multires_info(
  vertex_quantization_bits: int = 16,
  transform: Optional[Sequence[float]] = None,
  sharding: Optional[dict] = None,
  mip: int = 0,
) -> dict:
  """The multires mesh dir's info file
  (reference configure_multires_info, task_creation/mesh.py:437-479)."""
  info = {
    "@type": "neuroglancer_multilod_draco",
    "vertex_quantization_bits": int(vertex_quantization_bits),
    "transform": list(transform) if transform is not None
    else [1, 0, 0, 0, 0, 1, 0, 0, 0, 0, 1, 0],
    "lod_scale_multiplier": 1,
    "mip": int(mip),
  }
  if sharding is not None:
    info["sharding"] = sharding
  return info
