"""Chunk encodings for Precomputed volumes.

Byte-format parity targets (so Neuroglancer / the reference stack can read
outputs): ``raw``, ``compressed_segmentation``, ``jpeg``, ``png``. The
reference gets these from cloud-volume (see
/root/reference/igneous/task_creation/common.py:215-236 for the encodings
it routes); real EM image datasets are predominantly jpeg.

Layout conventions: in-memory chunks are numpy arrays with shape
(x, y, z, c). ``raw`` stores them Fortran-ordered, i.e. x varies fastest
in the byte stream and channel slowest — exactly the Precomputed "raw"
spec. ``jpeg``/``png`` store one 2D image of width x and height y*z (the
z slices stacked vertically), grayscale for 1 channel and RGB(A) for 3(4)
— the Precomputed image-codec layout Neuroglancer decodes.
"""

from __future__ import annotations

import io

import numpy as np

from .cseg import compress as cseg_compress, decompress as cseg_decompress

JPEG_DEFAULT_QUALITY = 85


def encode_raw(img: np.ndarray) -> bytes:
  # tobytes("F") on a strided view falls into numpy's element-wise slow
  # path (~4x slower than memcpy); consolidating to F-order first keeps
  # the whole encode at copy speed. Bytes are identical either way.
  if not img.flags.f_contiguous:
    img = np.asfortranarray(img)
  return img.tobytes("F")


def decode_raw(data: bytes, shape, dtype, writable: bool = True) -> np.ndarray:
  """``writable=False`` skips the defensive buffer copy and returns a
  read-only view of ``data`` — the download assembly path copies the
  decoded voxels into the output cutout anyway, so the extra copy here
  would be pure overhead at 8 bytes/voxel."""
  if writable:
    arr = np.frombuffer(bytearray(data), dtype=dtype)
  else:
    arr = np.frombuffer(data, dtype=dtype)
  return arr.reshape(shape, order="F")


def _to_image_plane(img: np.ndarray) -> np.ndarray:
  """(x, y, z, c) -> stacked 2D plane (y*z, x, c): z slices vertically."""
  x, y, z, c = img.shape
  return np.ascontiguousarray(img.transpose(2, 1, 0, 3)).reshape(z * y, x, c)


def _from_image_plane(plane: np.ndarray, shape) -> np.ndarray:
  x, y, z, c = shape
  if plane.ndim == 2:
    plane = plane[..., np.newaxis]
  if plane.shape[0] != z * y or plane.shape[1] != x:
    raise ValueError(
      f"decoded image plane {plane.shape} does not match chunk {shape}"
    )
  return np.asfortranarray(plane.reshape(z, y, x, c).transpose(2, 1, 0, 3))


def encode_jpeg(img: np.ndarray, quality: int = JPEG_DEFAULT_QUALITY) -> bytes:
  from PIL import Image

  if img.dtype != np.uint8:
    raise ValueError(f"jpeg requires uint8 chunks, got {img.dtype}")
  if img.shape[3] not in (1, 3):
    raise ValueError(f"jpeg supports 1 or 3 channels, got {img.shape[3]}")
  plane = _to_image_plane(img)
  pil = Image.fromarray(plane[..., 0] if plane.shape[2] == 1 else plane)
  bio = io.BytesIO()
  pil.save(bio, format="JPEG", quality=int(quality))
  return bio.getvalue()


def decode_jpeg(data: bytes, shape, dtype) -> np.ndarray:
  from PIL import Image

  plane = np.asarray(Image.open(io.BytesIO(data)))
  return _from_image_plane(plane, shape).astype(dtype, copy=False)


def encode_png(img: np.ndarray, compress_level: int = 6) -> bytes:
  from PIL import Image

  c = img.shape[3]
  if img.dtype == np.uint8:
    if c not in (1, 3, 4):
      raise ValueError(f"png supports 1/3/4 uint8 channels, got {c}")
    plane = _to_image_plane(img)
    pil = Image.fromarray(plane[..., 0] if c == 1 else plane)
  elif img.dtype == np.uint16:
    if c != 1:
      raise ValueError(f"png uint16 supports 1 channel, got {c}")
    pil = Image.fromarray(_to_image_plane(img)[..., 0])  # mode I;16
  else:
    raise ValueError(f"png requires uint8/uint16 chunks, got {img.dtype}")
  bio = io.BytesIO()
  pil.save(bio, format="PNG", compress_level=int(compress_level))
  return bio.getvalue()


def decode_png(data: bytes, shape, dtype) -> np.ndarray:
  from PIL import Image

  pil = Image.open(io.BytesIO(data))
  if np.dtype(dtype) == np.uint16 and pil.mode == "I":
    plane = np.asarray(pil).astype(np.uint16)
  else:
    plane = np.asarray(pil)
  return _from_image_plane(plane, shape).astype(dtype, copy=False)


def encode(
  img: np.ndarray, encoding: str, block_size=(8, 8, 8),
  jpeg_quality: int = JPEG_DEFAULT_QUALITY, png_level: int = 6,
) -> bytes:
  if img.ndim == 3:
    img = img[..., np.newaxis]
  if encoding == "raw":
    return encode_raw(img)
  if encoding == "compressed_segmentation":
    return cseg_compress(img, block_size=block_size)
  if encoding == "jpeg":
    return encode_jpeg(img, quality=jpeg_quality)
  if encoding == "png":
    return encode_png(img, compress_level=png_level)
  if encoding in ("compresso", "compresso-cpsx"):
    # "compresso-cpsx" is how info files advertise our experimental
    # container (meta.advertised_encoding); both names hit one codec
    from .compresso import compress as compresso_compress

    return compresso_compress(img)
  raise NotImplementedError(f"Encoding not supported: {encoding}")


def decode(data: bytes, encoding: str, shape, dtype, block_size=(8, 8, 8),
           writable: bool = True) -> np.ndarray:
  shape = tuple(int(v) for v in shape)
  if len(shape) == 3:
    shape = shape + (1,)
  if encoding == "raw":
    return decode_raw(data, shape, dtype, writable=writable)
  if encoding == "compressed_segmentation":
    return cseg_decompress(data, shape, dtype, block_size=block_size)
  if encoding == "jpeg":
    return decode_jpeg(data, shape, dtype)
  if encoding == "png":
    return decode_png(data, shape, dtype)
  if encoding in ("compresso", "compresso-cpsx"):
    from .compresso import decompress as compresso_decompress

    return compresso_decompress(data, shape, dtype)
  raise NotImplementedError(f"Encoding not supported: {encoding}")
