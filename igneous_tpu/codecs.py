"""Chunk encodings for Precomputed volumes.

Byte-format parity targets (so Neuroglancer / the reference stack can read
outputs): ``raw`` and ``compressed_segmentation``. The reference gets these
from cloud-volume (see /root/reference/igneous/task_creation/common.py:215-236
for the encodings it routes).

Layout convention: in-memory chunks are numpy arrays with shape (x, y, z, c).
``raw`` stores them Fortran-ordered, i.e. x varies fastest in the byte stream
and channel slowest — exactly the Precomputed "raw" spec.
"""

from __future__ import annotations

import numpy as np

from .cseg import compress as cseg_compress, decompress as cseg_decompress


def encode_raw(img: np.ndarray) -> bytes:
  return img.tobytes("F")


def decode_raw(data: bytes, shape, dtype) -> np.ndarray:
  arr = np.frombuffer(bytearray(data), dtype=dtype)
  return arr.reshape(shape, order="F")


def encode(img: np.ndarray, encoding: str, block_size=(8, 8, 8)) -> bytes:
  if img.ndim == 3:
    img = img[..., np.newaxis]
  if encoding == "raw":
    return encode_raw(img)
  if encoding == "compressed_segmentation":
    return cseg_compress(img, block_size=block_size)
  raise NotImplementedError(f"Encoding not supported: {encoding}")


def decode(data: bytes, encoding: str, shape, dtype, block_size=(8, 8, 8)) -> np.ndarray:
  shape = tuple(int(v) for v in shape)
  if len(shape) == 3:
    shape = shape + (1,)
  if encoding == "raw":
    return decode_raw(data, shape, dtype)
  if encoding == "compressed_segmentation":
    return cseg_decompress(data, shape, dtype, block_size=block_size)
  raise NotImplementedError(f"Encoding not supported: {encoding}")
