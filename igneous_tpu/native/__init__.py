"""Native (C++) runtime components, loaded via ctypes.

The reference keeps its per-voxel host codecs in compiled C++ packages
(SURVEY.md §2.3); igneous_tpu builds its equivalents from ``csrc/`` on
first use with the system toolchain and falls back to the pure-numpy
implementations when no compiler is available
(set IGNEOUS_TPU_NO_NATIVE=1 to force the fallback).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

from ..analysis import knobs

_HERE = os.path.dirname(os.path.abspath(__file__))
_CSRC = os.path.join(_HERE, "csrc")
_BUILD = os.path.join(_HERE, "build")

_lock = threading.Lock()
_libs = {}
_failed = set()


def _build_lib(name: str) -> Optional[str]:
  src = os.path.join(_CSRC, f"{name}.cpp")
  # content-hash in the artifact name: staleness is decided by the source
  # bytes, never by mtimes (git checkouts do not preserve them)
  with open(src, "rb") as f:
    digest = hashlib.sha256(f.read()).hexdigest()[:12]
  out = os.path.join(_BUILD, f"lib{name}-{digest}.so")
  if os.path.exists(out):
    return out
  os.makedirs(_BUILD, exist_ok=True)
  tmp = out + f".tmp{os.getpid()}"
  cmd = [
    "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
    "-o", tmp, src,
  ]
  try:
    subprocess.run(cmd, check=True, capture_output=True, timeout=120)
  except Exception:
    return None
  os.replace(tmp, out)
  return out


def load(name: str) -> Optional[ctypes.CDLL]:
  """Compile (if needed) and load csrc/<name>.cpp; None on any failure."""
  if knobs.get_bool("IGNEOUS_TPU_NO_NATIVE"):
    return None
  with _lock:
    if name in _libs:
      return _libs[name]
    if name in _failed:
      return None
    path = _build_lib(name)
    if path is None:
      _failed.add(name)
      return None
    try:
      lib = ctypes.CDLL(path)
    except OSError:
      _failed.add(name)
      return None
    _libs[name] = lib
    return lib


def edt_lib() -> Optional[ctypes.CDLL]:
  lib = load("edt")
  if lib is None:
    return None
  if not getattr(lib, "_configured", False):
    for fn in (lib.edt_ml_sq32, lib.edt_ml_sq64):
      fn.restype = None
      fn.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_long, ctypes.c_long, ctypes.c_long,
        ctypes.c_double, ctypes.c_double, ctypes.c_double,
        ctypes.c_int,
      ]
    lib._configured = True
  return lib


def ccl_lib() -> Optional[ctypes.CDLL]:
  lib = load("ccl")
  if lib is None:
    return None
  if not getattr(lib, "_configured", False):
    for fn in (lib.ccl_ml32, lib.ccl_ml64):
      fn.restype = ctypes.c_long
      fn.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_long, ctypes.c_long, ctypes.c_long,
        ctypes.c_int,
      ]
    lib._configured = True
  return lib


def pooling_lib() -> Optional[ctypes.CDLL]:
  lib = load("pooling")
  if lib is None:
    return None
  if not getattr(lib, "_configured", False):
    lib.pool_avg_u8.restype = None
    lib.pool_avg_u8.argtypes = [
      ctypes.c_void_p, ctypes.c_void_p,
      ctypes.c_long, ctypes.c_long, ctypes.c_long,
      ctypes.c_long, ctypes.c_long, ctypes.c_long,
      ctypes.c_int,
    ]
    lib.pool_mode_u64.restype = None
    lib.pool_mode_u64.argtypes = [
      ctypes.c_void_p, ctypes.c_void_p,
      ctypes.c_long, ctypes.c_long, ctypes.c_long,
      ctypes.c_long, ctypes.c_long, ctypes.c_long,
      ctypes.c_int, ctypes.c_int,
    ]
    lib.pool_mode_u64_f.restype = None
    lib.pool_mode_u64_f.argtypes = list(lib.pool_mode_u64.argtypes)
    lib._configured = True
  return lib


def dijkstra_lib() -> Optional[ctypes.CDLL]:
  lib = load("dijkstra")
  if lib is None:
    return None
  if not getattr(lib, "_configured", False):
    lib.igdij_update.restype = ctypes.c_int
    lib.igdij_update.argtypes = [
      ctypes.c_int64,
      ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
      ctypes.c_void_p, ctypes.c_void_p,
      ctypes.c_void_p, ctypes.c_int64,
    ]
    lib._configured = True
  return lib


def fggraph_lib() -> Optional[ctypes.CDLL]:
  lib = load("fggraph")
  if lib is None:
    return None
  if not getattr(lib, "_configured", False):
    lib.ig_fggraph.restype = ctypes.c_int64
    lib.ig_fggraph.argtypes = [
      ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
      ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
      ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
      ctypes.c_int64,
      ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
      ctypes.c_int32,
    ]
    lib._configured = True
  return lib


def cseg_lib() -> Optional[ctypes.CDLL]:
  lib = load("cseg")
  if lib is None:
    return None
  if not getattr(lib, "_configured", False):
    lib.cseg_encode_channel_strided.restype = ctypes.c_int64
    lib.cseg_encode_channel_strided.argtypes = [
      ctypes.c_void_p, ctypes.c_int,
      ctypes.c_int, ctypes.c_int, ctypes.c_int,
      ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
      ctypes.c_int, ctypes.c_int, ctypes.c_int,
      ctypes.POINTER(ctypes.POINTER(ctypes.c_uint32)),
    ]
    lib.cseg_free.restype = None
    lib.cseg_free.argtypes = [ctypes.POINTER(ctypes.c_uint32)]
    lib.cseg_decode_channel.restype = ctypes.c_int
    lib.cseg_decode_channel.argtypes = [
      ctypes.c_void_p, ctypes.c_int64, ctypes.c_int,
      ctypes.c_int, ctypes.c_int, ctypes.c_int,
      ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_void_p,
    ]
    lib._configured = True
  return lib


def xsection_lib() -> Optional[ctypes.CDLL]:
  lib = load("xsection")
  if lib is None:
    return None
  if not getattr(lib, "_configured", False):
    lib.xs_plane_cubes_area.restype = ctypes.c_double
    lib.xs_plane_cubes_area.argtypes = [
      ctypes.c_void_p, ctypes.c_longlong,
      ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
    ]
    lib._configured = True
  return lib


def simplify_lib() -> Optional[ctypes.CDLL]:
  lib = load("simplify")
  if lib is None:
    return None
  if not getattr(lib, "_configured", False):
    lib.igsimp_simplify.restype = ctypes.c_int
    lib.igsimp_simplify.argtypes = [
      ctypes.c_void_p, ctypes.c_int64,
      ctypes.c_void_p, ctypes.c_int64,
      ctypes.c_int64, ctypes.c_double, ctypes.c_int,
      ctypes.c_void_p, ctypes.c_void_p,
      ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
    ]
    lib._configured = True
  return lib
