// Native compressed_segmentation codec core.
//
// The reference pipeline links the compressed-segmentation C++ library via
// cloud-volume (SURVEY.md §2.3 "compression/codec stack"); this is the
// equivalent native hot path for igneous_tpu, produced and consumed through
// igneous_tpu/cseg.py. The bitstream matches the pure-numpy implementation
// exactly (including the share-previous-table rule) so either side can
// decode the other's output.
//
// Build: g++ -O3 -shared -fPIC -o libcseg.so cseg.cpp  (see native/__init__.py)

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

int pick_bits(int n_distinct) {
  static const int valid[] = {0, 1, 2, 4, 8, 16, 32};
  int need = 0;
  while ((1 << need) < n_distinct) need++;
  for (int b : valid)
    if (b >= need) return b;
  return -1;
}

// encode one channel: img is an (sx, sy, sz) array with ELEMENT strides
// (stx, sty, stz) — any layout (C, Fortran, sliced views). Voxels inside
// a block are enumerated x-fastest regardless of memory layout (the
// format fixes the traversal; strides only change where we read).
template <typename T>
std::vector<uint32_t> encode_channel(const T* img, int sx, int sy, int sz,
                                     int64_t stx, int64_t sty, int64_t stz,
                                     int bx, int by, int bz) {
  const int gx = (sx + bx - 1) / bx;
  const int gy = (sy + by - 1) / by;
  const int gz = (sz + bz - 1) / bz;
  const int64_t nblocks = (int64_t)gx * gy * gz;
  const int words_per_entry = sizeof(T) == 8 ? 2 : 1;

  std::vector<uint32_t> headers(nblocks * 2, 0);
  std::vector<uint32_t> body;
  body.reserve(nblocks * 4);

  std::vector<T> prev_table;
  uint32_t prev_table_offset = 0;

  std::vector<T> vals;
  std::vector<T> table;
  std::vector<uint32_t> idx;

  int64_t bi = 0;
  for (int z0 = 0; z0 < gz * bz; z0 += bz) {
    for (int y0 = 0; y0 < gy * by; y0 += by) {
      for (int x0 = 0; x0 < gx * bx; x0 += bx) {
        const int cx = x0 + bx > sx ? sx - x0 : bx;
        const int cy = y0 + by > sy ? sy - y0 : by;
        const int cz = z0 + bz > sz ? sz - z0 : bz;
        const int n = cx * cy * cz;

        // gather block voxels, x fastest
        vals.clear();
        vals.reserve(n);
        for (int dz = 0; dz < cz; dz++) {
          for (int dy = 0; dy < cy; dy++) {
            const T* row =
                img + (int64_t)(z0 + dz) * stz + (int64_t)(y0 + dy) * sty +
                (int64_t)x0 * stx;
            for (int dx = 0; dx < cx; dx++)
              vals.push_back(row[(int64_t)dx * stx]);
          }
        }

        // sorted distinct table + per-voxel index (matches np.unique order)
        table = vals;
        std::sort(table.begin(), table.end());
        table.erase(std::unique(table.begin(), table.end()), table.end());
        idx.clear();
        idx.reserve(n);
        for (const T v : vals) {
          const auto it = std::lower_bound(table.begin(), table.end(), v);
          idx.push_back((uint32_t)(it - table.begin()));
        }

        const int bits = pick_bits((int)table.size());
        if (bits < 0) return {};  // cannot happen for <= 2^32 distinct

        uint32_t table_offset;
        if (!prev_table.empty() && prev_table == table) {
          table_offset = prev_table_offset;
        } else {
          table_offset = (uint32_t)(2 * nblocks + body.size());
          for (const T v : table) {
            body.push_back((uint32_t)(v & 0xFFFFFFFFu));
            if (words_per_entry == 2)
              body.push_back((uint32_t)(((uint64_t)v) >> 32));
          }
          prev_table = table;
          prev_table_offset = table_offset;
        }
        if (table_offset >= (1u << 24)) return {};

        const uint32_t values_offset = (uint32_t)(2 * nblocks + body.size());
        if (bits > 0) {
          const int vals_per_word = 32 / bits;
          const int nwords = (n + vals_per_word - 1) / vals_per_word;
          for (int w = 0; w < nwords; w++) {
            uint32_t packed = 0;
            for (int k = 0; k < vals_per_word; k++) {
              const int i = w * vals_per_word + k;
              if (i < n) packed |= idx[i] << (k * bits);
            }
            body.push_back(packed);
          }
        }

        headers[2 * bi] = table_offset | ((uint32_t)bits << 24);
        headers[2 * bi + 1] = values_offset;
        bi++;
      }
    }
  }

  std::vector<uint32_t> out;
  out.reserve(headers.size() + body.size());
  out.insert(out.end(), headers.begin(), headers.end());
  out.insert(out.end(), body.begin(), body.end());
  return out;
}

template <typename T>
int decode_channel(const uint32_t* words, int64_t nwords, int sx, int sy,
                   int sz, int bx, int by, int bz, T* out) {
  const int gx = (sx + bx - 1) / bx;
  const int gy = (sy + by - 1) / by;
  const int gz = (sz + bz - 1) / bz;
  const int words_per_entry = sizeof(T) == 8 ? 2 : 1;

  int64_t bi = 0;
  for (int z0 = 0; z0 < gz * bz; z0 += bz) {
    for (int y0 = 0; y0 < gy * by; y0 += by) {
      for (int x0 = 0; x0 < gx * bx; x0 += bx) {
        if (2 * bi + 1 >= nwords) return 1;
        const uint32_t w0 = words[2 * bi];
        const uint32_t w1 = words[2 * bi + 1];
        const int bits = (int)(w0 >> 24);
        const int64_t table_offset = (int64_t)(w0 & 0xFFFFFF);
        const int64_t values_offset = (int64_t)w1;
        const int cx = x0 + bx > sx ? sx - x0 : bx;
        const int cy = y0 + by > sy ? sy - y0 : by;
        const int cz = z0 + bz > sz ? sz - z0 : bz;
        const int n = cx * cy * cz;

        int i = 0;
        for (int dz = 0; dz < cz; dz++) {
          for (int dy = 0; dy < cy; dy++) {
            for (int dx = 0; dx < cx; dx++, i++) {
              uint32_t index = 0;
              if (bits > 0) {
                const int vals_per_word = 32 / bits;
                const int64_t w = values_offset + i / vals_per_word;
                if (w >= nwords) return 2;
                const int shift = (i % vals_per_word) * bits;
                const uint32_t mask =
                    bits >= 32 ? 0xFFFFFFFFu : ((1u << bits) - 1u);
                index = (words[w] >> shift) & mask;
              }
              const int64_t t = table_offset + (int64_t)index * words_per_entry;
              if (t + words_per_entry - 1 >= nwords) return 3;
              T v = (T)words[t];
              if (words_per_entry == 2)
                v |= (T)(((uint64_t)words[t + 1]) << 32);
              out[(int64_t)(x0 + dx) * sy * sz + (int64_t)(y0 + dy) * sz +
                  (z0 + dz)] = v;
            }
          }
        }
        bi++;
      }
    }
  }
  return 0;
}

}  // namespace

extern "C" {

// Returns number of uint32 words written to *out (malloc'd; caller frees
// with cseg_free), or 0 on failure.
int64_t cseg_encode_channel_strided(const void* img, int is64, int sx,
                                    int sy, int sz, int64_t stx, int64_t sty,
                                    int64_t stz, int bx, int by, int bz,
                                    uint32_t** out) {
  std::vector<uint32_t> enc =
      is64 ? encode_channel<uint64_t>((const uint64_t*)img, sx, sy, sz, stx,
                                      sty, stz, bx, by, bz)
           : encode_channel<uint32_t>((const uint32_t*)img, sx, sy, sz, stx,
                                      sty, stz, bx, by, bz);
  if (enc.empty() && (int64_t)sx * sy * sz > 0) {
    const int gx = (sx + bx - 1) / bx, gy = (sy + by - 1) / by,
              gz = (sz + bz - 1) / bz;
    if ((int64_t)gx * gy * gz > 0) return 0;  // genuine failure
  }
  *out = (uint32_t*)std::malloc(enc.size() * 4);
  if (!*out) return 0;
  std::memcpy(*out, enc.data(), enc.size() * 4);
  return (int64_t)enc.size();
}

void cseg_free(uint32_t* p) { std::free(p); }

int cseg_decode_channel(const uint32_t* words, int64_t nwords, int is64,
                        int sx, int sy, int sz, int bx, int by, int bz,
                        void* out) {
  return is64 ? decode_channel<uint64_t>(words, nwords, sx, sy, sz, bx, by, bz,
                                         (uint64_t*)out)
              : decode_channel<uint32_t>(words, nwords, sx, sy, sz, bx, by, bz,
                                         (uint32_t*)out);
}
}
