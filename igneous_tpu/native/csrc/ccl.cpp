// Multilabel connected-components labeling (host path) — cc3d parity.
//
// Classic two-pass union-find over a (z, y, x) C-contiguous volume
// (x fastest — Fortran scan order for the package's (x, y, z) arrays, so
// first-appearance output numbering matches the device kernel's
// renumbering exactly). Two voxels connect iff their input labels are
// equal and nonzero; connectivity 6/18/26 selects the backward neighbor
// stencil. The device kernel (ops/ccl.py) stays the TPU batched path;
// this is the CPU production path, ~3 orders of magnitude faster than
// running the pointer-doubling kernel on the XLA CPU backend.

#include <cstddef>
#include <cstdint>
#include <vector>

namespace {

struct UF {
  std::vector<int32_t> parent;
  int32_t make() {
    parent.push_back((int32_t)parent.size());
    return (int32_t)(parent.size() - 1);
  }
  int32_t find(int32_t x) {
    int32_t root = x;
    while (parent[(size_t)root] != root) root = parent[(size_t)root];
    while (parent[(size_t)x] != root) {
      int32_t next = parent[(size_t)x];
      parent[(size_t)x] = root;
      x = next;
    }
    return root;
  }
  void unite(int32_t a, int32_t b) {
    int32_t ra = find(a), rb = find(b);
    if (ra != rb) parent[(size_t)(ra > rb ? ra : rb)] = (ra > rb ? rb : ra);
  }
};

// backward neighbors (already-scanned) for scan order z outer, y, x inner
// over a (z, y, x) C-contiguous array; entries are (dz, dy, dx) <= 0 ...
// lexicographically before the current voxel.
static const int OFFS26[13][3] = {
    {-1, -1, -1}, {-1, -1, 0}, {-1, -1, 1}, {-1, 0, -1}, {-1, 0, 0},
    {-1, 0, 1},   {-1, 1, -1}, {-1, 1, 0},  {-1, 1, 1},  {0, -1, -1},
    {0, -1, 0},   {0, -1, 1},  {0, 0, -1},
};
static const int IDX18[9] = {1, 3, 4, 5, 7, 9, 10, 11, 12};  // degree <= 2
static const int IDX6[3] = {4, 10, 12};                      // faces only

template <typename LabT>
static long ccl_impl(const LabT *lab, int32_t *out, long nz, long ny,
                     long nx, int connectivity) {
  const long sy = nx, sz = ny * nx;
  UF uf;
  uf.parent.reserve(1024);

  const int(*offs)[3] = OFFS26;
  std::vector<int> pick;
  if (connectivity == 26) {
    for (int i = 0; i < 13; ++i) pick.push_back(i);
  } else if (connectivity == 18) {
    pick.assign(IDX18, IDX18 + 9);
  } else {
    pick.assign(IDX6, IDX6 + 3);
  }

  // pass 1: provisional labels + unions
  for (long z = 0; z < nz; ++z) {
    for (long y = 0; y < ny; ++y) {
      const long base = z * sz + y * sy;
      for (long x = 0; x < nx; ++x) {
        const long i = base + x;
        const LabT v = lab[i];
        if (v == 0) {
          out[i] = -1;
          continue;
        }
        int32_t assigned = -1;
        for (int pi : pick) {
          const int dz = offs[pi][0], dy = offs[pi][1], dx = offs[pi][2];
          const long zz = z + dz, yy = y + dy, xx = x + dx;
          if (zz < 0 || yy < 0 || yy >= ny || xx < 0 || xx >= nx) continue;
          const long j = zz * sz + yy * sy + xx;
          if (lab[j] != v) continue;
          const int32_t pl = out[j];
          if (assigned < 0) {
            assigned = pl;
          } else if (pl != assigned) {
            uf.unite(assigned, pl);
          }
        }
        out[i] = (assigned >= 0) ? assigned : uf.make();
      }
    }
  }

  // pass 2: resolve + renumber by first appearance in scan order
  std::vector<int32_t> dense(uf.parent.size(), 0);
  int32_t next_id = 0;
  const long n = nz * ny * nx;
  for (long i = 0; i < n; ++i) {
    if (out[i] < 0) {
      out[i] = 0;
      continue;
    }
    const int32_t root = uf.find(out[i]);
    if (dense[(size_t)root] == 0) dense[(size_t)root] = ++next_id;
    out[i] = dense[(size_t)root];
  }
  return (long)next_id;
}

}  // namespace

extern "C" long ccl_ml32(const int32_t *lab, int32_t *out, long nz, long ny,
                         long nx, int connectivity) {
  return ccl_impl<int32_t>(lab, out, nz, ny, nx, connectivity);
}

extern "C" long ccl_ml64(const int64_t *lab, int32_t *out, long nz, long ny,
                         long nx, int connectivity) {
  return ccl_impl<int64_t>(lab, out, nz, ny, nx, connectivity);
}
