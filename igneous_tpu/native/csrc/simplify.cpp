// Error-bounded greedy QEM edge-collapse mesh simplification.
//
// Capability equivalent of the reference's zmesh `simplify`
// (reference igneous/tasks/mesh/mesh.py:371-383) and pyfqmr LOD
// reduction (reference igneous/tasks/mesh/multires.py:308-359): a
// Garland-Heckbert quadric error metric driven by a min-heap of edge
// collapses, with
//   * area-weighted face-plane quadrics,
//   * border-edge constraint quadrics (perpendicular penalty planes),
//   * optimal vertex placement (3x3 solve, endpoint/midpoint fallback),
//   * manifold-pinch (link condition) and normal-flip rejection,
//   * a physical-units error bound: collapsing stops once the cheapest
//     remaining collapse's area-weighted quadric cost exceeds max_error^2.
//
// Exposed as a C ABI for the ctypes loader in native/__init__.py.
// Deterministic: no threads, no randomness; heap ties break on vertex ids.

#include <cstdint>
#include <cmath>
#include <cstring>
#include <vector>
#include <queue>
#include <algorithm>
#include <unordered_map>

namespace {

// symmetric 4x4 quadric, upper triangle:
// [0]=xx [1]=xy [2]=xz [3]=xd [4]=yy [5]=yd... laid out:
//   0:aa 1:ab 2:ac 3:ad 4:bb 5:bc 6:bd 7:cc 8:cd 9:dd
struct Quadric {
  double m[10];
  void zero() { std::memset(m, 0, sizeof(m)); }
  void add_plane(double a, double b, double c, double d, double w) {
    m[0] += w * a * a; m[1] += w * a * b; m[2] += w * a * c; m[3] += w * a * d;
    m[4] += w * b * b; m[5] += w * b * c; m[6] += w * b * d;
    m[7] += w * c * c; m[8] += w * c * d;
    m[9] += w * d * d;
  }
  void add(const Quadric& o) { for (int i = 0; i < 10; i++) m[i] += o.m[i]; }
  double eval(double x, double y, double z) const {
    return m[0]*x*x + 2*m[1]*x*y + 2*m[2]*x*z + 2*m[3]*x
         + m[4]*y*y + 2*m[5]*y*z + 2*m[6]*y
         + m[7]*z*z + 2*m[8]*z
         + m[9];
  }
  // minimize: solve [A|b] from the gradient; false if near-singular
  bool optimal(double out[3]) const {
    const double a00 = m[0], a01 = m[1], a02 = m[2];
    const double a11 = m[4], a12 = m[5], a22 = m[7];
    const double b0 = -m[3], b1 = -m[6], b2 = -m[8];
    const double c00 = a11 * a22 - a12 * a12;
    const double c01 = a02 * a12 - a01 * a22;
    const double c02 = a01 * a12 - a02 * a11;
    const double det = a00 * c00 + a01 * c01 + a02 * c02;
    double scale = std::fabs(a00) + std::fabs(a01) + std::fabs(a02)
                 + std::fabs(a11) + std::fabs(a12) + std::fabs(a22);
    if (std::fabs(det) <= 1e-10 * scale * scale * scale + 1e-300) return false;
    const double c11 = a00 * a22 - a02 * a02;
    const double c12 = a01 * a02 - a00 * a12;
    const double c22 = a00 * a11 - a01 * a01;
    out[0] = (c00 * b0 + c01 * b1 + c02 * b2) / det;
    out[1] = (c01 * b0 + c11 * b1 + c12 * b2) / det;
    out[2] = (c02 * b0 + c12 * b1 + c22 * b2) / det;
    return true;
  }
};

struct HeapEntry {
  double cost;
  int v0, v1;
  uint32_t g0, g1;  // vertex generations at push time (lazy invalidation)
  double px, py, pz;
};
struct HeapCmp {
  bool operator()(const HeapEntry& a, const HeapEntry& b) const {
    if (a.cost != b.cost) return a.cost > b.cost;
    if (a.v0 != b.v0) return a.v0 > b.v0;
    return a.v1 > b.v1;
  }
};

struct Simplifier {
  int64_t nv, nf;
  std::vector<double> pos;           // 3*nv
  std::vector<Quadric> Q;            // per-vertex accumulated quadric
  std::vector<int> faces;            // 3*nf (rewritten in place on collapse)
  std::vector<uint8_t> face_alive;
  std::vector<uint8_t> vert_alive;
  std::vector<uint32_t> gen;         // bumped on every change to a vertex
  std::vector<std::vector<int>> inc; // vertex -> incident face ids
  std::priority_queue<HeapEntry, std::vector<HeapEntry>, HeapCmp> heap;
  int64_t live_faces;
  // epoch-marking scratch for O(deg) neighbor dedup / intersection
  // (replaces per-pop sort+unique+set_intersection, the run()-loop cost
  // center at ~60k collapses/sec before)
  std::vector<uint32_t> mark;
  uint32_t epoch = 0;

  void init(const float* v, int64_t nv_, const uint32_t* f, int64_t nf_,
            int preserve_border) {
    nv = nv_; nf = nf_;
    pos.resize(3 * nv);
    for (int64_t i = 0; i < 3 * nv; i++) pos[i] = v[i];
    faces.resize(3 * nf);
    for (int64_t i = 0; i < 3 * nf; i++) faces[i] = (int)f[i];
    face_alive.assign(nf, 1);
    vert_alive.assign(nv, 1);
    gen.assign(nv, 0);
    Q.assign(nv, Quadric());
    for (auto& q : Q) q.zero();
    inc.assign(nv, {});
    live_faces = 0;

    // Pass 1: connectivity, undirected edge usage (border detection), and
    // the mean face area. Plane weights are area/mean_area so quadric
    // costs stay in length^2 units regardless of the mesh's physical
    // resolution — max_error^2 is then a meaningful bound at any voxel
    // size (a raw area weighting made the bound ~zero collapses for
    // nm-scale meshes and far too loose for sub-voxel ones).
    std::unordered_map<uint64_t, int> edge_faces;
    edge_faces.reserve(nf * 3);
    double area_sum = 0.0;
    int64_t area_count = 0;
    for (int64_t t = 0; t < nf; t++) {
      int a = faces[3*t], b = faces[3*t+1], c = faces[3*t+2];
      if (a == b || b == c || a == c) { face_alive[t] = 0; continue; }
      live_faces++;
      inc[a].push_back((int)t);
      inc[b].push_back((int)t);
      inc[c].push_back((int)t);
      double n[3], area2;
      face_normal(t, n, area2);
      if (area2 >= 1e-30) {
        area_sum += 0.5 * std::sqrt(area2);
        area_count++;
      }
      for (int k = 0; k < 3; k++) {
        int u = faces[3*t+k], w = faces[3*t+(k+1)%3];
        uint64_t key = ekey(u, w);
        edge_faces[key]++;
      }
    }
    const double mean_area =
        (area_count > 0) ? (area_sum / area_count) : 1.0;
    const double wnorm = (mean_area > 1e-30) ? (1.0 / mean_area) : 1.0;

    // Pass 2: accumulate normalized-area-weighted plane quadrics.
    for (int64_t t = 0; t < nf; t++) {
      if (!face_alive[t]) continue;
      double n[3], area2;
      face_normal(t, n, area2);
      if (area2 < 1e-30) continue;
      double area = 0.5 * std::sqrt(area2);
      double inv = 1.0 / std::sqrt(area2);
      double nx = n[0]*inv, ny = n[1]*inv, nz = n[2]*inv;
      int a = faces[3*t];
      double d = -(nx*pos[3*a] + ny*pos[3*a+1] + nz*pos[3*a+2]);
      for (int k = 0; k < 3; k++) {
        int vtx = faces[3*t+k];
        Q[vtx].add_plane(nx, ny, nz, d, area * wnorm);
      }
    }

    // border constraint: for every edge used by exactly one face, add a
    // heavy plane through the edge perpendicular to that face so the open
    // boundary (e.g. a chunk wall) cannot drift
    if (preserve_border) {
      for (int64_t t = 0; t < nf; t++) {
        if (!face_alive[t]) continue;
        double n[3], area2;
        face_normal(t, n, area2);
        if (area2 < 1e-30) continue;
        double ninv = 1.0 / std::sqrt(area2);
        for (int k = 0; k < 3; k++) {
          int u = faces[3*t+k], w = faces[3*t+(k+1)%3];
          auto it = edge_faces.find(ekey(u, w));
          if (it == edge_faces.end() || it->second != 1) continue;
          double ex = pos[3*w] - pos[3*u];
          double ey = pos[3*w+1] - pos[3*u+1];
          double ez = pos[3*w+2] - pos[3*u+2];
          // perpendicular plane normal = edge x face-normal
          double bx = ey * n[2]*ninv - ez * n[1]*ninv;
          double by = ez * n[0]*ninv - ex * n[2]*ninv;
          double bz = ex * n[1]*ninv - ey * n[0]*ninv;
          double bl = std::sqrt(bx*bx + by*by + bz*bz);
          if (bl < 1e-20) continue;
          bx /= bl; by /= bl; bz /= bl;
          double bd = -(bx*pos[3*u] + by*pos[3*u+1] + bz*pos[3*u+2]);
          double elen2 = ex*ex + ey*ey + ez*ez;
          // heavy relative to the ~O(1) normalized interior weights
          double wgt = 1e3 * elen2 * wnorm;
          Q[u].add_plane(bx, by, bz, bd, wgt);
          Q[w].add_plane(bx, by, bz, bd, wgt);
        }
      }
    }

    // seed the heap with every unique edge
    for (auto& kv : edge_faces) {
      int u = (int)(kv.first >> 32), w = (int)(kv.first & 0xffffffffu);
      push_edge(u, w);
    }
  }

  static uint64_t ekey(int u, int w) {
    if (u > w) std::swap(u, w);
    return ((uint64_t)(uint32_t)u << 32) | (uint32_t)w;
  }

  void face_normal(int64_t t, double n[3], double& len2) const {
    const int a = faces[3*t], b = faces[3*t+1], c = faces[3*t+2];
    const double* pa = &pos[3*a];
    const double* pb = &pos[3*b];
    const double* pc = &pos[3*c];
    double ux = pb[0]-pa[0], uy = pb[1]-pa[1], uz = pb[2]-pa[2];
    double vx = pc[0]-pa[0], vy = pc[1]-pa[1], vz = pc[2]-pa[2];
    n[0] = uy*vz - uz*vy; n[1] = uz*vx - ux*vz; n[2] = ux*vy - uy*vx;
    len2 = n[0]*n[0] + n[1]*n[1] + n[2]*n[2];
  }

  void candidate(int u, int w, double p[3], double& cost) const {
    Quadric Qe = Q[u];
    Qe.add(Q[w]);
    if (!Qe.optimal(p)) {
      // fallback: best of endpoints + midpoint
      const double* pu = &pos[3*u];
      const double* pw = &pos[3*w];
      double mid[3] = {(pu[0]+pw[0])/2, (pu[1]+pw[1])/2, (pu[2]+pw[2])/2};
      double cu = Qe.eval(pu[0], pu[1], pu[2]);
      double cw = Qe.eval(pw[0], pw[1], pw[2]);
      double cm = Qe.eval(mid[0], mid[1], mid[2]);
      if (cu <= cw && cu <= cm) { p[0]=pu[0]; p[1]=pu[1]; p[2]=pu[2]; cost = cu; }
      else if (cw <= cm)        { p[0]=pw[0]; p[1]=pw[1]; p[2]=pw[2]; cost = cw; }
      else                      { p[0]=mid[0]; p[1]=mid[1]; p[2]=mid[2]; cost = cm; }
    } else {
      cost = Qe.eval(p[0], p[1], p[2]);
    }
    if (cost < 0) cost = 0;  // numerical noise
  }

  void push_edge(int u, int w) {
    if (!vert_alive[u] || !vert_alive[w] || u == w) return;
    double p[3], cost;
    candidate(u, w, p, cost);
    heap.push({cost, u, w, gen[u], gen[w], p[0], p[1], p[2]});
  }

  // vertices adjacent to v over live faces (deduplicated via epoch
  // marks, O(deg); order is incidence order — the heap comparator is
  // total on (cost, v0, v1) so push order never changes pop order)
  void neighbors(int v, std::vector<int>& out) {
    out.clear();
    if (mark.size() != (size_t)nv) mark.assign(nv, 0);
    if (epoch == 0xffffffffu) {  // wrap: clear stale marks
      mark.assign(nv, 0);
      epoch = 0;
    }
    uint32_t e = ++epoch;
    for (int t : inc[v]) {
      if (!face_alive[t]) continue;
      for (int k = 0; k < 3; k++) {
        int u = faces[3*t+k];
        if (u != v && mark[u] != e) {
          mark[u] = e;
          out.push_back(u);
        }
      }
    }
  }

  // |neighbors(v0) ∩ neighbors(v1)| without materializing either set
  // sorted: mark v0's neighborhood, scan v1's
  int64_t shared_neighbors(int v0, int v1, std::vector<int>& nb_v) {
    neighbors(v0, nb_v);
    uint32_t e = epoch;  // nb_v's marks
    int64_t shared = 0;
    for (int t : inc[v1]) {
      if (!face_alive[t]) continue;
      for (int k = 0; k < 3; k++) {
        int u = faces[3*t+k];
        if (u != v1 && mark[u] == e) {
          mark[u] = 0;  // count each shared vertex once
          shared++;
        }
      }
    }
    return shared;
  }

  // would moving vertex v to p flip or squash any of its live faces that
  // do not contain the disappearing vertex `other`?
  bool flips(int v, int other, const double p[3]) const {
    for (int t : inc[v]) {
      if (!face_alive[t]) continue;
      int a = faces[3*t], b = faces[3*t+1], c = faces[3*t+2];
      if (a == other || b == other || c == other) continue;  // dies anyway
      double n0[3], l0;
      face_normal(t, n0, l0);
      // recompute with v at p
      double pa[3] = {pos[3*a], pos[3*a+1], pos[3*a+2]};
      double pb[3] = {pos[3*b], pos[3*b+1], pos[3*b+2]};
      double pc[3] = {pos[3*c], pos[3*c+1], pos[3*c+2]};
      double* tgt = (a == v) ? pa : (b == v) ? pb : pc;
      tgt[0] = p[0]; tgt[1] = p[1]; tgt[2] = p[2];
      double ux = pb[0]-pa[0], uy = pb[1]-pa[1], uz = pb[2]-pa[2];
      double vx = pc[0]-pa[0], vy = pc[1]-pa[1], vz = pc[2]-pa[2];
      double n1[3] = {uy*vz - uz*vy, uz*vx - ux*vz, ux*vy - uy*vx};
      double l1 = n1[0]*n1[0] + n1[1]*n1[1] + n1[2]*n1[2];
      if (l1 < 1e-24) return true;  // squashed to zero area
      double dot = n0[0]*n1[0] + n0[1]*n1[1] + n0[2]*n1[2];
      if (l0 >= 1e-24 && dot <= 0) return true;  // flipped
    }
    return false;
  }

  // collapse w into v at position p
  void collapse(int v, int w, const double p[3]) {
    pos[3*v] = p[0]; pos[3*v+1] = p[1]; pos[3*v+2] = p[2];
    Q[v].add(Q[w]);
    for (int t : inc[w]) {
      if (!face_alive[t]) continue;
      int* fv = &faces[3*t];
      bool has_v = (fv[0] == v || fv[1] == v || fv[2] == v);
      if (has_v) {
        face_alive[t] = 0;
        live_faces--;
      } else {
        for (int k = 0; k < 3; k++) if (fv[k] == w) fv[k] = v;
        inc[v].push_back(t);
      }
    }
    inc[w].clear();
    inc[w].shrink_to_fit();
    vert_alive[w] = 0;
    gen[v]++;
    gen[w]++;
    // drop dead faces from v's incidence so it cannot grow unboundedly
    auto& iv = inc[v];
    iv.erase(std::remove_if(iv.begin(), iv.end(),
                            [&](int t) { return !face_alive[t]; }),
             iv.end());
    std::sort(iv.begin(), iv.end());
    iv.erase(std::unique(iv.begin(), iv.end()), iv.end());
  }

  void run(int64_t target_faces, double max_error) {
    const double max_cost = (max_error > 0) ? max_error * max_error : -1.0;
    std::vector<int> nb_v;
    while (live_faces > target_faces && !heap.empty()) {
      HeapEntry e = heap.top();
      heap.pop();
      if (!vert_alive[e.v0] || !vert_alive[e.v1]) continue;
      if (gen[e.v0] != e.g0 || gen[e.v1] != e.g1) continue;  // stale
      // error bound: the quadric cost is the area-weighted sum of squared
      // point-plane distances, so max_error^2 caps the collapse once the
      // represented surface patch deviates ~max_error physical units
      if (max_cost >= 0 && e.cost > max_cost) break;
      // link condition: the common neighborhood of (v0,v1) must be
      // exactly the apex vertices of the faces the edge bounds; extra
      // shared neighbors mean the collapse would pinch the surface
      int64_t shared = shared_neighbors(e.v0, e.v1, nb_v);
      int edge_face_count = 0;
      for (int t : inc[e.v0]) {
        if (!face_alive[t]) continue;
        int a = faces[3*t], b = faces[3*t+1], c = faces[3*t+2];
        bool hasw = (a == e.v1 || b == e.v1 || c == e.v1);
        if (hasw) edge_face_count++;
      }
      if (shared > edge_face_count) continue;
      double p[3] = {e.px, e.py, e.pz};
      if (flips(e.v0, e.v1, p) || flips(e.v1, e.v0, p)) continue;
      collapse(e.v0, e.v1, p);
      // refresh the surviving vertex's edge candidates
      neighbors(e.v0, nb_v);
      for (int u : nb_v) push_edge(e.v0, u);
    }
  }

  void emit(float* vout, uint32_t* fout, int64_t* out_nv, int64_t* out_nf) {
    std::vector<int64_t> remap(nv, -1);
    int64_t cv = 0;
    for (int64_t i = 0; i < nv; i++) {
      if (!vert_alive[i]) continue;
      // only emit vertices still referenced by a live face
      bool used = false;
      for (int t : inc[i]) if (face_alive[t]) { used = true; break; }
      if (!used) continue;
      remap[i] = cv;
      vout[3*cv]   = (float)pos[3*i];
      vout[3*cv+1] = (float)pos[3*i+1];
      vout[3*cv+2] = (float)pos[3*i+2];
      cv++;
    }
    int64_t cf = 0;
    for (int64_t t = 0; t < nf; t++) {
      if (!face_alive[t]) continue;
      int a = faces[3*t], b = faces[3*t+1], c = faces[3*t+2];
      if (a == b || b == c || a == c) continue;
      if (remap[a] < 0 || remap[b] < 0 || remap[c] < 0) continue;
      fout[3*cf]   = (uint32_t)remap[a];
      fout[3*cf+1] = (uint32_t)remap[b];
      fout[3*cf+2] = (uint32_t)remap[c];
      cf++;
    }
    *out_nv = cv;
    *out_nf = cf;
  }
};

}  // namespace

extern "C" {

// Returns 0 on success. Output buffers must hold nv*3 floats / nf*3
// uint32 (simplification never grows a mesh).
int igsimp_simplify(
    const float* verts, int64_t nv,
    const uint32_t* faces, int64_t nf,
    int64_t target_faces, double max_error, int preserve_border,
    float* verts_out, uint32_t* faces_out,
    int64_t* out_nv, int64_t* out_nf) {
  if (nv <= 0 || nf <= 0) { *out_nv = 0; *out_nf = 0; return 0; }
  Simplifier s;
  s.init(verts, nv, faces, nf, preserve_border);
  s.run(target_faces < 4 ? 4 : target_faces, max_error);
  s.emit(verts_out, faces_out, out_nv, out_nf);
  return 0;
}

}  // extern "C"
