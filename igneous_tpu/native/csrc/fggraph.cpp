// 26-connected foreground-graph CSR builder for the TEASAR trace.
//
// The numpy builder (ops/skeletonize.py _foreground_graph) assembles 13
// directional boolean slices, concatenates COO triples, converts to CSR
// and symmetrizes with `g + g.T` — ~20% of the skeleton forge wall on
// blob fixtures (BASELINE.md round-5 profile). This builds the final
// symmetric CSR directly in two passes over the voxel grid.
//
// Conventions match the numpy builder exactly:
//   * node ids = C-order scan positions of foreground voxels;
//   * edge weight = (pdrf[a] + pdrf[b]) * 0.5 * physical step length;
//   * optional voxel_graph (uint32 direction bitfields): the edge for
//     positive-lex delta p between voxels (a, a+p) exists iff bit
//     bits[p] is set at a (the lower voxel) — the kimimaro movement
//     constraint the graphene autapse fix uses.

#include <cstdint>
#include <cstring>

namespace {

struct Dir {
  int dx, dy, dz;
  double len;
  int bit;       // voxel_graph bit for the positive-lex form
  bool positive; // is (dx,dy,dz) the positive-lex form?
};

} // namespace

extern "C" {

// Pass 1: per-node neighbor counts -> indptr (n+1), returns nnz.
// Pass 2 (fill=1): fill indices (int32) + weights (double) using indptr.
// idx: int64 per-voxel node id (-1 = background), C-order (z fastest).
int64_t ig_fggraph(
  int64_t nx, int64_t ny, int64_t nz,
  const int64_t* idx,
  const float* pdrf,
  const uint32_t* vg,            // nullable
  const int8_t* deltas,          // 13 x 3 positive-lex deltas
  const double* step_len,        // 13 physical lengths
  const int32_t* bits,           // 13 voxel_graph bits
  int64_t n,                     // number of foreground nodes
  int64_t* indptr,               // n+1
  int32_t* indices,              // nnz (fill pass)
  double* weights,               // nnz (fill pass)
  int32_t fill
) {
  Dir dirs[26];
  for (int k = 0; k < 13; ++k) {
    dirs[k] = Dir{deltas[3 * k], deltas[3 * k + 1], deltas[3 * k + 2],
                  step_len[k], bits[k], true};
    dirs[13 + k] = Dir{-deltas[3 * k], -deltas[3 * k + 1],
                       -deltas[3 * k + 2], step_len[k], bits[k], false};
  }
  const int64_t sy = nz, sx = ny * nz;
  if (!fill) {
    for (int64_t i = 0; i <= n; ++i) indptr[i] = 0;
  }
  // nodes are visited exactly once, in node-id order (ids are assigned
  // by the same C-order scan), so a local write cursor starting at
  // indptr[node] fills each CSR row completely without extra state
  for (int64_t x = 0; x < nx; ++x) {
    for (int64_t y = 0; y < ny; ++y) {
      const int64_t base = x * sx + y * sy;
      for (int64_t z = 0; z < nz; ++z) {
        const int64_t a = base + z;
        const int64_t ia = idx[a];
        if (ia < 0) continue;
        int64_t w = fill ? indptr[ia] : 0;
        for (int k = 0; k < 26; ++k) {
          const Dir& d = dirs[k];
          const int64_t ux = x + d.dx, uy = y + d.dy, uz = z + d.dz;
          if (ux < 0 || ux >= nx || uy < 0 || uy >= ny ||
              uz < 0 || uz >= nz) continue;
          const int64_t b = ux * sx + uy * sy + uz;
          const int64_t ib = idx[b];
          if (ib < 0) continue;
          if (vg) {
            const int64_t src = d.positive ? a : b;
            if (((vg[src] >> d.bit) & 1u) == 0) continue;
          }
          if (!fill) {
            indptr[ia + 1]++;
          } else {
            indices[w] = (int32_t)ib;
            weights[w] = (double)(pdrf[a] + pdrf[b]) * 0.5 * d.len;
            ++w;
          }
        }
      }
    }
  }
  if (!fill) {
    int64_t acc = 0;
    for (int64_t i = 1; i <= n; ++i) {
      acc += indptr[i];
      indptr[i] = acc;
    }
    return acc;
  }
  return 0;
}

} // extern "C"
