// Native CPU pooling comparator — the bench baseline.
//
// Round-1 bench credited the repo's own numpy oracles (x8-core) as the
// "reference CPU"; this is the tighter C-level comparator VERDICT asked
// for: hand-rolled average and mode pooling at memory-bound speed, the
// closest in-image stand-in for tinybrain's C kernels (which cannot be
// vendored in a zero-egress build). Semantics match ops/oracle.py
// exactly — round-half-up integer averaging; mode with max-count ties
// broken by earliest window position in z-major (fz, fy, fx) order — so
// the comparator is itself oracle-verified by tests.
//
// Arrays are C-contiguous (x, y, z); threading splits the output x range.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

// windows clamp at the high edge (edge padding, matching the oracle)
static inline long clamp_idx(long v, long n) { return v < n ? v : n - 1; }

static void avg_u8_range(const uint8_t *in, uint8_t *out, long nx, long ny,
                         long nz, long fx, long fy, long fz, long ox0,
                         long ox1) {
  const long oy = (ny + fy - 1) / fy, oz = (nz + fz - 1) / fz;
  const long n = fx * fy * fz;
  const long syx = ny * nz;  // x stride
  const long syy = nz;       // y stride
  for (long x = ox0; x < ox1; ++x) {
    for (long y = 0; y < oy; ++y) {
      for (long z = 0; z < oz; ++z) {
        long acc = 0;
        for (long dx = 0; dx < fx; ++dx) {
          const long sx = clamp_idx(x * fx + dx, nx);
          for (long dy = 0; dy < fy; ++dy) {
            const long sy = clamp_idx(y * fy + dy, ny);
            const uint8_t *row = in + sx * syx + sy * syy;
            for (long dz = 0; dz < fz; ++dz) {
              acc += row[clamp_idx(z * fz + dz, nz)];
            }
          }
        }
        out[x * oy * oz + y * oz + z] = (uint8_t)((acc + n / 2) / n);
      }
    }
  }
}

static void mode_u64_range(const uint64_t *in, uint64_t *out, long nx,
                           long ny, long nz, long fx, long fy, long fz,
                           int sparse, long ox0, long ox1) {
  const long oy = (ny + fy - 1) / fy, oz = (nz + fz - 1) / fz;
  const long n = fx * fy * fz;
  const long syx = ny * nz, syy = nz;
  std::vector<uint64_t> vals((size_t)n);
  for (long x = ox0; x < ox1; ++x) {
    for (long y = 0; y < oy; ++y) {
      for (long z = 0; z < oz; ++z) {
        // gather in z-major window order (dz outer, then dy, then dx) to
        // match the oracle's tie-breaking position index
        long k = 0;
        for (long dz = 0; dz < fz; ++dz) {
          const long sz = clamp_idx(z * fz + dz, nz);
          for (long dy = 0; dy < fy; ++dy) {
            const long sy = clamp_idx(y * fy + dy, ny);
            for (long dx = 0; dx < fx; ++dx) {
              const long sx = clamp_idx(x * fx + dx, nx);
              vals[(size_t)k++] = in[sx * syx + sy * syy + sz];
            }
          }
        }
        long best = -1, best_count = -1;
        for (long i = 0; i < n; ++i) {
          if (sparse && vals[(size_t)i] == 0) continue;
          long count = 0;
          for (long j = 0; j < n; ++j) count += (vals[(size_t)j] == vals[(size_t)i]);
          if (count > best_count) {
            best_count = count;
            best = i;
          }
        }
        out[x * oy * oz + y * oz + z] = (best < 0) ? 0 : vals[(size_t)best];
      }
    }
  }
}

template <typename F>
static void run_threaded(long ox, int parallel, F body) {
  int T = parallel > 0 ? parallel : (int)std::thread::hardware_concurrency();
  if (T < 1) T = 1;
  T = (int)std::min<long>(T, ox);
  if (T <= 1) {
    body(0L, ox);
    return;
  }
  std::vector<std::thread> threads;
  const long per = (ox + T - 1) / T;
  for (int t = 0; t < T; ++t) {
    const long lo = (long)t * per, hi = std::min(ox, lo + per);
    if (lo >= hi) break;
    threads.emplace_back(body, lo, hi);
  }
  for (auto &th : threads) th.join();
}

extern "C" void pool_avg_u8(const uint8_t *in, uint8_t *out, long nx,
                            long ny, long nz, long fx, long fy, long fz,
                            int parallel) {
  const long ox = (nx + fx - 1) / fx;
  run_threaded(ox, parallel, [&](long lo, long hi) {
    avg_u8_range(in, out, nx, ny, nz, fx, fy, fz, lo, hi);
  });
}

extern "C" void pool_mode_u64(const uint64_t *in, uint64_t *out, long nx,
                              long ny, long nz, long fx, long fy, long fz,
                              int sparse, int parallel) {
  const long ox = (nx + fx - 1) / fx;
  run_threaded(ox, parallel, [&](long lo, long hi) {
    mode_u64_range(in, out, nx, ny, nz, fx, fy, fz, sparse, lo, hi);
  });
}
