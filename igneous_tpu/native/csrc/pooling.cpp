// Native CPU pooling comparator — the bench baseline.
//
// Round-1 bench credited the repo's own numpy oracles (x8-core) as the
// "reference CPU"; this is the tighter C-level comparator VERDICT asked
// for: hand-rolled average and mode pooling at memory-bound speed, the
// closest in-image stand-in for tinybrain's C kernels (which cannot be
// vendored in a zero-egress build). Semantics match ops/oracle.py
// exactly — round-half-up integer averaging; mode with max-count ties
// broken by earliest window position in z-major (fz, fy, fx) order — so
// the comparator is itself oracle-verified by tests.
//
// Arrays are C-contiguous (x, y, z); threading splits the output x range.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

// windows clamp at the high edge (edge padding, matching the oracle)
static inline long clamp_idx(long v, long n) { return v < n ? v : n - 1; }

static void avg_u8_generic(const uint8_t *in, uint8_t *out, long nx, long ny,
                           long nz, long fx, long fy, long fz, long x,
                           long y) {
  // one (x, y) output column, any factor, clamped (edge-replicating)
  const long oy = (ny + fy - 1) / fy, oz = (nz + fz - 1) / fz;
  const long n = fx * fy * fz;
  const long syx = ny * nz, syy = nz;
  for (long z = 0; z < oz; ++z) {
    long acc = 0;
    for (long dx = 0; dx < fx; ++dx) {
      const long sx = clamp_idx(x * fx + dx, nx);
      for (long dy = 0; dy < fy; ++dy) {
        const long sy = clamp_idx(y * fy + dy, ny);
        const uint8_t *row = in + sx * syx + sy * syy;
        for (long dz = 0; dz < fz; ++dz) {
          acc += row[clamp_idx(z * fz + dz, nz)];
        }
      }
    }
    out[x * oy * oz + y * oz + z] = (uint8_t)((acc + n / 2) / n);
  }
}

static void avg_u8_range(const uint8_t *in, uint8_t *out, long nx, long ny,
                         long nz, long fx, long fy, long fz, long ox0,
                         long ox1) {
  const long oy = (ny + fy - 1) / fy, oz = (nz + fz - 1) / fz;
  const long syx = ny * nz;  // x stride
  const long syy = nz;       // y stride
  // interior extents where no window needs clamping
  const long ix = nx / fx, iy = ny / fy, iz = nz / fz;
  const bool f221 = (fx == 2 && fy == 2 && fz == 1);
  const bool f222 = (fx == 2 && fy == 2 && fz == 2);
  const bool f122 = (fx == 1 && fy == 2 && fz == 2);
  for (long x = ox0; x < ox1; ++x) {
    const bool x_in = x < ix;
    if (f122) {
      // (1,2,2): x untouched — pool the (y, z) plane; the transposed-call
      // form of a logical 2x2x1 pool on Fortran-ordered data
      const uint8_t *cx = in + x * syx;
      uint8_t *ox_ = out + x * oy * oz;
      for (long y = 0; y < iy; ++y) {
        const uint8_t *r0 = cx + (y * 2) * syy;
        const uint8_t *r1 = r0 + syy;
        uint8_t *o = ox_ + y * oz;
        for (long z = 0; z < iz; ++z) {
          const long s = 2 * z;
          o[z] = (uint8_t)(((unsigned)r0[s] + r0[s + 1] + r1[s] +
                            r1[s + 1] + 2u) >> 2);
        }
        if (iz < oz) {
          const long s = nz - 1;
          o[iz] = (uint8_t)((2u * ((unsigned)r0[s] + r1[s]) + 2u) >> 2);
        }
      }
      for (long y = iy; y < oy; ++y) {
        avg_u8_generic(in, out, nx, ny, nz, fx, fy, fz, x, y);
      }
      continue;
    }
    for (long y = 0; y < oy; ++y) {
      if ((f221 || f222) && x_in && y < iy) {
        // clamp-free rows: the inner z loop is contiguous and
        // auto-vectorizes (this is where ~all voxels of a 2x2x{1,2}
        // pyramid live — boundary columns fall through to the
        // clamped generic path below)
        const uint8_t *r00 = in + (x * 2) * syx + (y * 2) * syy;
        const uint8_t *r01 = r00 + syy;
        const uint8_t *r10 = r00 + syx;
        const uint8_t *r11 = r10 + syy;
        uint8_t *o = out + x * oy * oz + y * oz;
        if (f221) {
          for (long z = 0; z < oz; ++z) {
            o[z] = (uint8_t)(((unsigned)r00[z] + r01[z] + r10[z] + r11[z] +
                              2u) >> 2);
          }
        } else {
          for (long z = 0; z < iz; ++z) {
            const long s = 2 * z;
            o[z] = (uint8_t)(((unsigned)r00[s] + r00[s + 1] + r01[s] +
                              r01[s + 1] + r10[s] + r10[s + 1] + r11[s] +
                              r11[s + 1] + 4u) >> 3);
          }
          if (iz < oz) {  // odd nz: last output plane replicates the edge
            const long s = 2 * iz < nz ? 2 * iz : nz - 1;
            o[iz] = (uint8_t)((2u * ((unsigned)r00[s] + r01[s] + r10[s] +
                                     r11[s]) + 4u) >> 3);
          }
        }
        continue;
      }
      avg_u8_generic(in, out, nx, ny, nz, fx, fy, fz, x, y);
    }
  }
}

static inline uint64_t mode_vote(const uint64_t *vals, long n, int sparse) {
  long best = -1, best_count = -1;
  for (long i = 0; i < n; ++i) {
    if (sparse && vals[i] == 0) continue;
    long count = 0;
    for (long j = 0; j < n; ++j) count += (vals[j] == vals[i]);
    if (count > best_count) {
      best_count = count;
      best = i;
    }
  }
  return (best < 0) ? 0 : vals[best];
}

static void mode_u64_range(const uint64_t *in, uint64_t *out, long nx,
                           long ny, long nz, long fx, long fy, long fz,
                           int sparse, long ox0, long ox1) {
  const long oy = (ny + fy - 1) / fy, oz = (nz + fz - 1) / fz;
  const long n = fx * fy * fz;
  const long syx = ny * nz, syy = nz;
  const long ix = nx / fx, iy = ny / fy, iz = nz / fz;
  const bool f221 = (fx == 2 && fy == 2 && fz == 1);
  const bool f122 = (fx == 1 && fy == 2 && fz == 2);
  std::vector<uint64_t> vals((size_t)n);
  for (long x = ox0; x < ox1; ++x) {
    if (f122) {
      // (1,2,2): the transposed-call form of a logical 2x2x1 mode pool on
      // Fortran-ordered data. Tie-breaking note: for a 2x2 window the
      // value at corner (0,0) has the minimum position index under BOTH
      // traversal orders, and any maximal-count tie always includes that
      // corner's value or a unique count-2 value — so this order is
      // exactly equivalent to the logical (dx fastest) order (see
      // tests: host path vs oracle across transposed layouts).
      const uint64_t *cx = in + x * syx;
      uint64_t *ox_ = out + x * oy * oz;
      for (long y = 0; y < iy; ++y) {
        const uint64_t *r0 = cx + (y * 2) * syy;
        const uint64_t *r1 = r0 + syy;
        uint64_t *o = ox_ + y * oz;
        for (long z = 0; z < iz; ++z) {
          const long s = 2 * z;
          const uint64_t v0 = r0[s], v1 = r1[s], v2 = r0[s + 1],
                         v3 = r1[s + 1];
          uint64_t r;
          if (v0 == v1 && v1 == v2 && v2 == v3) {
            r = v0;
          } else if (!sparse) {
            if (v0 == v1 || v0 == v2 || v0 == v3) r = v0;
            else if (v1 == v2 || v1 == v3) r = v1;
            else if (v2 == v3) r = v2;
            else r = v0;
          } else {
            // kernel-logical position order (dy fastest for fx=1) — the
            // host layer only routes direct logical-(1,2,2) calls here;
            // transposed 2x2x1 calls come only in the non-sparse case,
            // where the waterfall above is order-independent (see
            // host_downsample's dispatch rules)
            const uint64_t w[4] = {v0, v1, v2, v3};
            r = mode_vote(w, 4, 1);
          }
          o[z] = r;
        }
        if (iz < oz) {
          const long s = nz - 1;
          // kernel-logical order with the z window clamped (both dz -> s)
          const uint64_t w[4] = {r0[s], r1[s], r0[s], r1[s]};
          o[iz] = mode_vote(w, 4, sparse);
        }
      }
      for (long y = iy; y < oy; ++y) {
        uint64_t *o = ox_ + y * oz;
        const long sy0 = clamp_idx(y * 2, ny), sy1 = clamp_idx(y * 2 + 1, ny);
        const uint64_t *r0 = cx + sy0 * syy;
        const uint64_t *r1 = cx + sy1 * syy;
        for (long z = 0; z < oz; ++z) {
          const long s0 = clamp_idx(z * 2, nz), s1 = clamp_idx(z * 2 + 1, nz);
          // kernel-logical position order (dy fastest)
          const uint64_t w[4] = {r0[s0], r1[s0], r0[s1], r1[s1]};
          o[z] = mode_vote(w, 4, sparse);
        }
      }
      continue;
    }
    if (f221 && x < ix) {
      // clamp-free 2x2x1 columns: direct row pointers, the exact
      // max-count/earliest-position vote as a branch waterfall.
      // Window position order is z-major → (dy, dx):
      //   v0=(0,0) v1=(0,1)=x+1 v2=(1,0)=y+1 v3=(1,1)
      const uint64_t *c00 = in + (x * 2) * syx;
      for (long y = 0; y < iy; ++y) {
        const uint64_t *r00 = c00 + (y * 2) * syy;
        const uint64_t *r01 = r00 + syy;       // y+1 → position v2
        const uint64_t *r10 = r00 + syx;       // x+1 → position v1
        const uint64_t *r11 = r10 + syy;
        uint64_t *o = out + x * oy * oz + y * oz;
        for (long z = 0; z < oz; ++z) {
          const uint64_t v0 = r00[z], v1 = r10[z], v2 = r01[z], v3 = r11[z];
          uint64_t r;
          if (v0 == v1 && v1 == v2 && v2 == v3) {
            r = v0;  // uniform window (the common case in real labels)
          } else if (!sparse) {
            // count>=2 for v0 means nothing both out-counts it and sits
            // earlier (a count-3 rival would have to include v0 itself)
            if (v0 == v1 || v0 == v2 || v0 == v3) r = v0;
            else if (v1 == v2 || v1 == v3) r = v1;
            else if (v2 == v3) r = v2;
            else r = v0;  // all distinct: earliest position wins
          } else {
            const uint64_t w[4] = {v0, v1, v2, v3};
            r = mode_vote(w, 4, 1);
          }
          o[z] = r;
        }
      }
      // boundary y columns (clamped) fall through to the generic path
      for (long y = iy; y < oy; ++y) {
        for (long z = 0; z < oz; ++z) {
          long k = 0;
          for (long dy = 0; dy < fy; ++dy) {
            const long sy = clamp_idx(y * fy + dy, ny);
            for (long dx = 0; dx < fx; ++dx) {
              const long sx = clamp_idx(x * fx + dx, nx);
              vals[(size_t)k++] = in[sx * syx + sy * syy + z];
            }
          }
          out[x * oy * oz + y * oz + z] = mode_vote(vals.data(), n, sparse);
        }
      }
      continue;
    }
    for (long y = 0; y < oy; ++y) {
      for (long z = 0; z < oz; ++z) {
        // gather in z-major window order (dz outer, then dy, then dx) to
        // match the oracle's tie-breaking position index
        long k = 0;
        for (long dz = 0; dz < fz; ++dz) {
          const long sz = clamp_idx(z * fz + dz, nz);
          for (long dy = 0; dy < fy; ++dy) {
            const long sy = clamp_idx(y * fy + dy, ny);
            for (long dx = 0; dx < fx; ++dx) {
              const long sx = clamp_idx(x * fx + dx, nx);
              vals[(size_t)k++] = in[sx * syx + sy * syy + sz];
            }
          }
        }
        // uniform-window early exit: real segmentation windows are
        // overwhelmingly single-label, so skip the O(n^2) vote
        bool uniform = true;
        for (long i = 1; i < n; ++i) uniform &= (vals[(size_t)i] == vals[0]);
        out[x * oy * oz + y * oz + z] =
          uniform ? vals[0] : mode_vote(vals.data(), n, sparse);
      }
    }
  }
}

static void mode_u64_f_range(const uint64_t *in, uint64_t *out, long nx,
                             long ny, long nz, long fx, long fy, long fz,
                             int sparse, long oz0, long oz1) {
  // Fortran-ordered logical (x, y, z) input (x contiguous) and output.
  // Output loops z, y outer and x inner (memory order); the per-window
  // gather runs dz, dy outer and dx INNER — the required earliest-
  // position tie order — so this is exact for ANY factor without the
  // transpose-equivalence argument. Threading splits the output z range.
  const long ox = (nx + fx - 1) / fx, oy = (ny + fy - 1) / fy;
  const long n = fx * fy * fz;
  const long sy = nx, sz = nx * ny;        // input Fortran strides
  const long osy = ox, osz = ox * oy;      // output Fortran strides
  std::vector<uint64_t> vals((size_t)n);
  for (long z = oz0; z < oz1; ++z) {
    for (long y = 0; y < oy; ++y) {
      uint64_t *orow = out + z * osz + y * osy;
      for (long x = 0; x < ox; ++x) {
        long k = 0;
        for (long dz = 0; dz < fz; ++dz) {
          const long izz = clamp_idx(z * fz + dz, nz);
          for (long dy = 0; dy < fy; ++dy) {
            const long iyy = clamp_idx(y * fy + dy, ny);
            const uint64_t *row = in + izz * sz + iyy * sy;
            for (long dx = 0; dx < fx; ++dx) {
              vals[(size_t)k++] = row[clamp_idx(x * fx + dx, nx)];
            }
          }
        }
        bool uniform = true;
        for (long i = 1; i < n; ++i) uniform &= (vals[(size_t)i] == vals[0]);
        orow[x] = uniform ? vals[0] : mode_vote(vals.data(), n, sparse);
      }
    }
  }
}

template <typename F>
static void run_threaded(long ox, int parallel, F body) {
  int T = parallel > 0 ? parallel : (int)std::thread::hardware_concurrency();
  if (T < 1) T = 1;
  T = (int)std::min<long>(T, ox);
  if (T <= 1) {
    body(0L, ox);
    return;
  }
  std::vector<std::thread> threads;
  const long per = (ox + T - 1) / T;
  for (int t = 0; t < T; ++t) {
    const long lo = (long)t * per, hi = std::min(ox, lo + per);
    if (lo >= hi) break;
    threads.emplace_back(body, lo, hi);
  }
  for (auto &th : threads) th.join();
}

extern "C" void pool_avg_u8(const uint8_t *in, uint8_t *out, long nx,
                            long ny, long nz, long fx, long fy, long fz,
                            int parallel) {
  const long ox = (nx + fx - 1) / fx;
  run_threaded(ox, parallel, [&](long lo, long hi) {
    avg_u8_range(in, out, nx, ny, nz, fx, fy, fz, lo, hi);
  });
}

extern "C" void pool_mode_u64(const uint64_t *in, uint64_t *out, long nx,
                              long ny, long nz, long fx, long fy, long fz,
                              int sparse, int parallel) {
  const long ox = (nx + fx - 1) / fx;
  run_threaded(ox, parallel, [&](long lo, long hi) {
    mode_u64_range(in, out, nx, ny, nz, fx, fy, fz, sparse, lo, hi);
  });
}

extern "C" void pool_mode_u64_f(const uint64_t *in, uint64_t *out, long nx,
                                long ny, long nz, long fx, long fy, long fz,
                                int sparse, int parallel) {
  const long oz = (nz + fz - 1) / fz;
  run_threaded(oz, parallel, [&](long lo, long hi) {
    mode_u64_f_range(in, out, nx, ny, nz, fx, fy, fz, sparse, lo, hi);
  });
}
