// Incremental multi-source Dijkstra over a CSR graph.
//
// TEASAR's fix_branching regrows a shortest-path forest from the whole
// current tree before every traced path (ops/skeletonize.py; reference
// behavior: kimimaro's fix_branching). A full recompute per path is
// O(E log V) every time — but adding sources S to an existing field only
// improves distances in the region closer to S than to the old tree, so
// seeding the heap with S against the WARM field relaxes exactly that
// region. The result equals a cold multi-source run from (old sources ∪
// S): both compute, per node, min over sources of the penalized path
// cost.
//
// dist/pred are caller-owned arrays persisted across calls:
//   igdij_update(n, indptr, indices, weights, dist, pred, sources, nsrc)
// Initial call: dist pre-filled with +inf, pred with -1, sources={root}.
// Deterministic: the heap orders by (distance, node id).
//
// Exposed as a C ABI for the ctypes loader in native/__init__.py.

#include <cstdint>
#include <queue>
#include <utility>
#include <vector>

extern "C" {

int igdij_update(
    int64_t n,
    const int64_t* indptr,      // n+1
    const int32_t* indices,     // nnz
    const double* weights,      // nnz
    double* dist,               // n, in/out
    int32_t* pred,              // n, in/out
    const int64_t* sources, int64_t nsrc) {
  using QE = std::pair<double, int32_t>;
  std::priority_queue<QE, std::vector<QE>, std::greater<QE>> heap;
  for (int64_t i = 0; i < nsrc; i++) {
    int64_t s = sources[i];
    if (s < 0 || s >= n) return 1;
    if (dist[s] > 0.0) {
      dist[s] = 0.0;
      pred[s] = -1;
    }
    heap.push({0.0, (int32_t)s});
  }
  while (!heap.empty()) {
    auto [d, u] = heap.top();
    heap.pop();
    if (d > dist[u]) continue;  // stale entry
    for (int64_t e = indptr[u]; e < indptr[u + 1]; e++) {
      int32_t v = indices[e];
      double nd = d + weights[e];
      if (nd < dist[v]) {
        dist[v] = nd;
        pred[v] = u;
        heap.push({nd, v});
      }
    }
  }
  return 0;
}

}  // extern "C"
