// Exact plane∩cube cross-section area — the xs3d-equivalent hot loop.
//
// ops/cross_section.py computes per-vertex slice areas by clipping a
// covering quad against each voxel cube (Sutherland-Hodgman, 6 planes)
// and summing shoelace areas. The numpy formulation pays fancy-indexing
// overhead on tiny (≤10-vertex) polygons; this is the same algorithm as
// scalar C++ with fixed-size stack arrays, numerically IDENTICAL to the
// Python twin (same 1e-9 inside tolerance, clamped interpolation, exact
// landing on the wall) so the equivalence test can require exactness.
//
// The reference outsources this inner loop to the xs3d C++ package
// (SURVEY.md §2.3; /root/reference/igneous/tasks/skeleton.py:449).

#include <cmath>
#include <cstdint>

namespace {

struct V3 {
  double x, y, z;
};

static inline V3 sub(V3 a, V3 b) { return {a.x - b.x, a.y - b.y, a.z - b.z}; }
static inline V3 add(V3 a, V3 b) { return {a.x + b.x, a.y + b.y, a.z + b.z}; }
static inline V3 mul(V3 a, double s) { return {a.x * s, a.y * s, a.z * s}; }
static inline double dot(V3 a, V3 b) {
  return a.x * b.x + a.y * b.y + a.z * b.z;
}
static inline V3 cross(V3 a, V3 b) {
  return {a.y * b.z - a.z * b.y, a.z * b.x - a.x * b.z,
          a.x * b.y - a.y * b.x};
}
static inline double comp(V3 a, int axis) {
  return axis == 0 ? a.x : (axis == 1 ? a.y : a.z);
}
static inline void setcomp(V3 &a, int axis, double v) {
  if (axis == 0) a.x = v;
  else if (axis == 1) a.y = v;
  else a.z = v;
}

// clip polygon (n verts) against sign*(p[axis]-bound) <= 0; returns new n
static int clip_one(const V3 *in, int n, V3 *out, int axis, double sign,
                    double bound) {
  int m = 0;
  for (int k = 0; k < n; ++k) {
    const int j = (k + 1 < n) ? k + 1 : 0;
    const V3 vi = in[k], vj = in[j];
    const double di = sign * (comp(vi, axis) - bound);
    const double dj = sign * (comp(vj, axis) - bound);
    const bool ini = di <= 1e-9, inj = dj <= 1e-9;
    if (ini) out[m++] = vi;
    if (ini != inj) {
      double t = di / (di - dj);
      if (t < 0.0) t = 0.0;
      if (t > 1.0) t = 1.0;
      V3 pt = add(vi, mul(sub(vj, vi), t));
      setcomp(pt, axis, bound);  // exact landing on the wall
      out[m++] = pt;
    }
  }
  return m;
}

}  // namespace

extern "C" double xs_plane_cubes_area(
    const long long *vox_idx, long long K, const double *v_phys,
    const double *t_unit, const double *anis) {
  const V3 v = {v_phys[0], v_phys[1], v_phys[2]};
  const V3 t = {t_unit[0], t_unit[1], t_unit[2]};
  const V3 a = {anis[0], anis[1], anis[2]};

  // plane basis (matches _plane_basis: e = unit on argmin |t| axis)
  int mi = 0;
  double mv = std::fabs(t.x);
  if (std::fabs(t.y) < mv) { mi = 1; mv = std::fabs(t.y); }
  if (std::fabs(t.z) < mv) { mi = 2; }
  V3 e = {0, 0, 0};
  setcomp(e, mi, 1.0);
  V3 u = cross(t, e);
  const double un = std::sqrt(dot(u, u));
  u = mul(u, 1.0 / un);
  const V3 w = cross(t, u);

  const double s = std::sqrt(dot(a, a));  // covers any cube cross-section
  const V3 su_pw = mul(add(u, w), s);
  const V3 su_mw = mul(sub(u, w), s);

  double total = 0.0;
  V3 poly[2][16];
  for (long long c = 0; c < K; ++c) {
    const V3 center = {vox_idx[3 * c + 0] * a.x, vox_idx[3 * c + 1] * a.y,
                       vox_idx[3 * c + 2] * a.z};
    const V3 lo = sub(center, mul(a, 0.5));
    const double d_c = dot(sub(center, v), t);
    const V3 p_rel = sub(sub(center, mul(t, d_c)), lo);
    poly[0][0] = add(p_rel, su_pw);
    poly[0][1] = add(p_rel, su_mw);
    poly[0][2] = sub(p_rel, su_pw);
    poly[0][3] = sub(p_rel, su_mw);
    int n = 4, cur = 0;
    for (int axis = 0; axis < 3 && n >= 3; ++axis) {
      n = clip_one(poly[cur], n, poly[1 - cur], axis, -1.0, 0.0);
      cur = 1 - cur;
      if (n < 3) break;
      n = clip_one(poly[cur], n, poly[1 - cur], axis, 1.0, comp(a, axis));
      cur = 1 - cur;
    }
    if (n < 3) continue;
    // shoelace: 0.5 * | sum_i (v_i - v_0) x (v_{i+1} - v_0) |
    V3 acc = {0, 0, 0};
    const V3 *p = poly[cur];
    for (int i = 1; i + 1 < n; ++i) {
      acc = add(acc, cross(sub(p[i], p[0]), sub(p[i + 1], p[0])));
    }
    total += 0.5 * std::sqrt(dot(acc, acc));
  }
  return total;
}
