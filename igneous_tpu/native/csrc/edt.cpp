// Multilabel anisotropic squared Euclidean distance transform (host path).
//
// Same three-pass decomposition as igneous_tpu/ops/edt.py (the device
// kernel is the semantics reference): per axis line, the answer is the
// min of (a) the squared distance to the voxel's own run edge — the best
// different-label contribution — and (b) a Felzenszwalb-Huttenlocher
// parabola envelope restricted to the voxel's own run — the best
// same-label contribution. O(n) per line, threaded over lines.
//
// Strided axes are processed through transposed line tiles: a naive
// strided walk puts consecutive line elements megabytes apart (the x-pass
// stride is ny*nz), costing a cache+TLB miss per voxel; copying tiles of
// TILE lines into contiguous local buffers makes every pass stream.
// Labels are compared by raw equality (32- or 64-bit), so callers never
// need a renumber pass. The reference reaches the same operation through
// kimimaro's bundled C++ `edt` package
// (/root/reference/igneous/tasks/skeleton.py:303).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

static const float INFF = 1e20f;
static const long TILE = 64;

// One contiguous line: reads lab/val, writes out (aliasing val is fine —
// values are consumed before being overwritten only via the local copy
// the caller made; here out writes are the only stores).
template <typename LabT>
static void line_pass(const LabT *lab, const float *val, float *out, long n,
                      double w2, bool first, int *vbuf, double *zbuf,
                      double *hbuf) {
  long a = 0;
  while (a < n) {
    const LabT L = lab[a];
    long b = a;
    while (b + 1 < n && lab[b + 1] == L) ++b;

    for (long i = a; i <= b; ++i) {
      double dl = (a > 0) ? (double)(i - a + 1) : 1e30;
      double dr = (b < n - 1) ? (double)(b + 1 - i) : 1e30;
      double d = std::min(dl, dr);
      double e = (d < 1e29) ? d * d * w2 : (double)INFF;
      out[i] = (float)std::min((double)INFF, e);
    }

    if (!first) {
      long k = -1;
      for (long q = a; q <= b; ++q) {
        double fq = val[q];
        if (fq >= INFF * 0.5) continue;
        fq /= w2;
        double s = -1e30;
        while (k >= 0) {
          const long vq = vbuf[k];
          s = ((fq + (double)q * q) - (hbuf[k] + (double)vq * vq)) /
              (2.0 * (double)(q - vq));
          if (s <= zbuf[k]) {
            --k;
          } else {
            break;
          }
        }
        if (k < 0) s = -1e30;
        ++k;
        vbuf[k] = (int)q;
        hbuf[k] = fq;
        zbuf[k] = s;
        zbuf[k + 1] = 1e30;
      }
      if (k >= 0) {
        long j = 0;
        for (long q = a; q <= b; ++q) {
          while (j < k && zbuf[j + 1] < (double)q) ++j;
          const double dq = (double)(q - vbuf[j]);
          const double env = (hbuf[j] + dq * dq) * w2;
          if (env < (double)out[q]) out[q] = (float)env;
        }
      }
    }
    a = b + 1;
  }
}

template <typename LabT> struct AxisJob {
  const LabT *lab;
  float *val;  // in-place across the pass
  long n, stride;  // line length and element stride
  double w2;
  bool first;
  long n_lines;
  long inner;                       // line l -> (o = l/inner, i = l%inner)
  long outer_stride, inner_stride;  // base = o*outer_stride + i*inner_stride
};

// Process lines [lo, hi) of the job. When inner_stride == 1, consecutive
// inner lines are gathered TILE at a time into transposed contiguous
// buffers (element q of tile line t sits at base + q*stride + t).
template <typename LabT>
static void axis_worker(const AxisJob<LabT> &job, long lo, long hi) {
  std::vector<int> vbuf(job.n + 1);
  std::vector<double> zbuf(job.n + 2), hbuf(job.n + 1);

  if (job.stride == 1) {
    std::vector<float> linebuf(job.n);
    for (long l = lo; l < hi; ++l) {
      const long o = l / job.inner, i = l % job.inner;
      float *v = job.val + o * job.outer_stride + i * job.inner_stride;
      const LabT *lb = job.lab + o * job.outer_stride + i * job.inner_stride;
      if (!job.first) std::memcpy(linebuf.data(), v, job.n * sizeof(float));
      line_pass(lb, linebuf.data(), v, job.n, job.w2, job.first, vbuf.data(),
                zbuf.data(), hbuf.data());
    }
    return;
  }

  std::vector<LabT> tlab(TILE * job.n);
  std::vector<float> tval(TILE * job.n), tout(TILE * job.n);
  long l = lo;
  while (l < hi) {
    const long o = l / job.inner, i = l % job.inner;
    long tile = std::min({(long)TILE, hi - l, job.inner - i});
    const long base = o * job.outer_stride + i * job.inner_stride;
    if (job.inner_stride == 1 && tile > 1) {
      // transposed gather: contiguous reads of `tile` elements per q
      for (long q = 0; q < job.n; ++q) {
        const LabT *ls = job.lab + base + q * job.stride;
        const float *vs = job.val + base + q * job.stride;
        for (long t = 0; t < tile; ++t) tlab[t * job.n + q] = ls[t];
        if (!job.first)
          for (long t = 0; t < tile; ++t) tval[t * job.n + q] = vs[t];
      }
      for (long t = 0; t < tile; ++t) {
        line_pass(tlab.data() + t * job.n, tval.data() + t * job.n,
                  tout.data() + t * job.n, job.n, job.w2, job.first,
                  vbuf.data(), zbuf.data(), hbuf.data());
      }
      for (long q = 0; q < job.n; ++q) {
        float *vd = job.val + base + q * job.stride;
        for (long t = 0; t < tile; ++t) vd[t] = tout[t * job.n + q];
      }
      l += tile;
    } else {
      // general strided line (rare: inner_stride != 1)
      for (long q = 0; q < job.n; ++q) {
        tlab[q] = job.lab[base + q * job.stride];
        if (!job.first) tval[q] = job.val[base + q * job.stride];
      }
      line_pass(tlab.data(), tval.data(), tout.data(), job.n, job.w2,
                job.first, vbuf.data(), zbuf.data(), hbuf.data());
      for (long q = 0; q < job.n; ++q)
        job.val[base + q * job.stride] = tout[q];
      l += 1;
    }
  }
}

template <typename LabT>
static void run_axis(const AxisJob<LabT> &job, int parallel) {
  int T = parallel > 0 ? parallel
                       : (int)std::thread::hardware_concurrency();
  if (T < 1) T = 1;
  T = (int)std::min<long>(T, (job.n_lines + TILE - 1) / TILE);
  if (T <= 1) {
    axis_worker(job, 0, job.n_lines);
    return;
  }
  std::vector<std::thread> threads;
  // chunk on tile boundaries so tiles never span workers
  const long tiles = (job.n_lines + TILE - 1) / TILE;
  const long per = ((tiles + T - 1) / T) * TILE;
  for (int t = 0; t < T; ++t) {
    const long lo = (long)t * per, hi = std::min(job.n_lines, lo + per);
    if (lo >= hi) break;
    threads.emplace_back([&job, lo, hi]() { axis_worker(job, lo, hi); });
  }
  for (auto &th : threads) th.join();
}

template <typename LabT>
static void edt_impl(const LabT *lab, float *out, long nx, long ny, long nz,
                     double wx, double wy, double wz, int parallel) {
  // C-contiguous (x, y, z): strides sx = ny*nz, sy = nz, sz = 1.
  const long sx = ny * nz, sy = nz, sz = 1;
  // pass along x (first: edge term only); lines over (y, z), inner z
  run_axis<LabT>({lab, out, nx, sx, wx * wx, true, ny * nz, nz, sy, sz},
                 parallel);
  // pass along y; lines over (x, z), inner z
  run_axis<LabT>({lab, out, ny, sy, wy * wy, false, nx * nz, nz, sx, sz},
                 parallel);
  // pass along z (contiguous); lines over (x, y), inner y
  run_axis<LabT>({lab, out, nz, sz, wz * wz, false, nx * ny, ny, sx, sy},
                 parallel);
}

extern "C" void edt_ml_sq32(const int32_t *lab, float *out, long nx, long ny,
                            long nz, double wx, double wy, double wz,
                            int parallel) {
  edt_impl<int32_t>(lab, out, nx, ny, nz, wx, wy, wz, parallel);
}

extern "C" void edt_ml_sq64(const int64_t *lab, float *out, long nx, long ny,
                            long nz, double wx, double wy, double wz,
                            int parallel) {
  edt_impl<int64_t>(lab, out, nx, ny, nz, wx, wy, wz, parallel);
}
