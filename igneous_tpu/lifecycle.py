"""Worker lifecycle: graceful preemption drain and zombie-safe exits.

Preemptible TPU VMs — the deployment the ROADMAP north-star targets —
kill workers with ~30s notice (GCE sends ACPI shutdown → SIGTERM via
the node agent). PR 1 contained tasks that crash; this module contains
workers that die: a drain request (signal, sentinel file, or the GCE
preemption metadata endpoint) flips a StopFlag that the poll loops
check between tasks, so the in-flight task finishes, still-leased batch
members return to the queue immediately, a final telemetry-counters
line is flushed, and the process exits EXIT_PREEMPTED — which the k8s
deployment treats as "preempted, not failed" (no CrashLoopBackOff).

The counterpart fencing — a *presumed-dead* worker that wakes up and
tries to complete a task the queue re-issued — lives in the queue
backends (FileQueue/SQSQueue delete/renew/nack reject stale lease
tokens with ``zombie.*`` counters).
"""

from __future__ import annotations

import os
import threading

from .analysis import knobs
from typing import Optional

# distinct from any Python/click failure code so the pod spec can map it:
# preempted workers restart quietly, real failures alarm
EXIT_PREEMPTED = 83


class StopFlag:
  """Thread-safe drain request; records the FIRST reason it was set."""

  def __init__(self):
    self._event = threading.Event()
    self._lock = threading.Lock()
    self.reason: Optional[str] = None  # guarded-by: self._lock

  def set(self, reason: str = "stop"):
    with self._lock:
      if self.reason is None:
        self.reason = reason
    self._event.set()
    # a drain request marks the active journal dirty so the poll loop's
    # next maybe_flush writes the final span batch BEFORE the pod dies
    # (signal-handler safe: only sets an event, no IO here)
    try:
      from .observability import journal, metrics

      journal.request_flush()
      # the health plane distinguishes "draining" from "stalled": a
      # draining worker's silence is expected, a stalled one's is not
      # (lock-free write: this can run inside a signal handler)
      metrics.gauge_set_async_safe("worker.draining", 1.0)
    except Exception:
      pass

  def is_set(self) -> bool:
    return self._event.is_set()

  def wait(self, timeout: Optional[float] = None) -> bool:
    return self._event.wait(timeout)


def install_signal_handlers(flag: StopFlag, signals=None):
  """Route SIGTERM/SIGINT into ``flag`` (graceful drain instead of an
  abrupt death mid-lease). Returns a restore() callable that reinstates
  the previous handlers — callers embedded in larger processes (tests,
  notebooks) must not leak handlers. Safe to call off the main thread
  (it becomes a no-op there; only processes own signal dispositions)."""
  import signal as signal_mod

  if signals is None:
    signals = (signal_mod.SIGTERM, signal_mod.SIGINT)
  previous = {}

  def handler(signum, frame):
    del frame
    try:
      name = signal_mod.Signals(signum).name
    except ValueError:
      name = f"signal-{signum}"
    flag.set(name)

  for sig in signals:
    try:
      previous[sig] = signal_mod.signal(sig, handler)
    except (ValueError, OSError):  # not the main thread / unsupported sig
      continue

  def restore():
    for sig, prev in previous.items():
      try:
        signal_mod.signal(sig, prev)
      except (ValueError, OSError):
        pass

  return restore


class PreemptionWatcher:
  """Daemon thread that flips ``flag`` when preemption is announced.

  Two pluggable sources, both optional (the watcher is inert without
  either — signals still work):

  * sentinel file (``IGNEOUS_PREEMPT_SENTINEL`` or ``sentinel=``): drain
    when the path exists. This is how tests — and operators without a
    metadata service — trigger a drain without signal delivery.
  * metadata endpoint (``IGNEOUS_PREEMPT_URL`` or ``metadata_url=``):
    polled with the ``Metadata-Flavor: Google`` header; a body of TRUE
    means the VM is being preempted (GCE:
    ``http://metadata.google.internal/computeMetadata/v1/instance/preempted``).
    Never enabled by default — this build is zero-egress unless the
    operator opts in.

  Poll cadence: ``IGNEOUS_PREEMPT_POLL_SEC`` (default 1s); the first
  check runs immediately on start.
  """

  def __init__(self, flag: StopFlag, sentinel: Optional[str] = None,
               metadata_url: Optional[str] = None,
               interval: Optional[float] = None):
    self.flag = flag
    self.sentinel = (
      sentinel if sentinel is not None
      else knobs.get_str("IGNEOUS_PREEMPT_SENTINEL")
    )
    self.metadata_url = (
      metadata_url if metadata_url is not None
      else knobs.get_str("IGNEOUS_PREEMPT_URL")
    )
    if interval is None:
      interval = knobs.get_float("IGNEOUS_PREEMPT_POLL_SEC")
    self.interval = float(interval)
    self._stop = threading.Event()
    self._thread: Optional[threading.Thread] = None

  def check(self) -> Optional[str]:
    """One poll; returns the drain reason or None."""
    if self.sentinel and os.path.exists(self.sentinel):
      return "sentinel"
    if self.metadata_url and self._metadata_preempted():
      return "preempted"
    return None

  def _metadata_preempted(self) -> bool:
    import urllib.request

    try:
      req = urllib.request.Request(
        self.metadata_url, headers={"Metadata-Flavor": "Google"}
      )
      with urllib.request.urlopen(req, timeout=2) as resp:
        return resp.read().strip().upper() == b"TRUE"
    except Exception:
      return False  # metadata hiccups must never kill a healthy worker

  def _run(self):
    while True:
      reason = self.check()
      if reason is not None:
        self.flag.set(reason)
        return
      if self._stop.wait(self.interval):
        return

  def start(self):
    if self._thread is not None or not (self.sentinel or self.metadata_url):
      return self
    self._thread = threading.Thread(
      target=self._run, daemon=True, name="preemption-watcher"
    )
    self._thread.start()
    return self

  def stop(self):
    self._stop.set()
    if self._thread is not None:
      self._thread.join(timeout=5.0)
      self._thread = None

  __enter__ = start

  def __exit__(self, *exc):
    self.stop()
    return False
