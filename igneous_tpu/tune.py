"""Per-device-kind tuned kernel configs + generate-and-measure autotuner
(ISSUE 19).

Today's ``IGNEOUS_CCL_TILE`` / ``IGNEOUS_PAGE_SHAPE`` defaults are a
one-off hand sweep frozen into the knob registry. ``igneous tune``
replaces that with generate-and-measure: sweep candidate tile shapes,
EDT line-block geometry, and page shape/batch on seeded representative
workloads, assert every candidate's output is byte-identical to the
default path (these knobs are performance-only by construction — any
divergence is a kernel bug and fails the sweep), and persist the winners
as ``tuned/<device_kind>.json`` next to the compile cache's executables.

Knob resolution order for the tunables, everywhere they are read::

    explicit env  >  tuned/<device_kind>.json  >  registry default

so an operator override always wins, a fleet with a published tuned
config picks it up with zero env plumbing, and everyone else keeps the
registry defaults. The config root is ``IGNEOUS_TUNE_CONFIG`` when set,
else ``IGNEOUS_COMPILE_CACHE`` — the moment a real TPU round runs, tuned
configs and warm executables land together as durable fleet artifacts.
"""

from __future__ import annotations

import json
import re
import time
from typing import Callable, Dict, List, Optional

from .analysis import knobs

CONFIG_ENV = "IGNEOUS_TUNE_CONFIG"
TUNED_PREFIX = "tuned/"

# every knob the autotuner sweeps and resolve() serves from tuned configs
TUNABLE = (
  "IGNEOUS_CCL_TILE",
  "IGNEOUS_EDT_LINE_BLOCK",
  "IGNEOUS_PAGE_SHAPE",
  "IGNEOUS_PAGE_BATCH",
)


def device_kind() -> str:
  """Filesystem-safe device kind for the tuned-config filename (e.g.
  ``cpu``, ``TPU_v4`` → ``TPU_v4``)."""
  try:
    import jax

    dev = jax.devices()[0]
    kind = dev.device_kind or dev.platform
  except Exception:
    kind = "unknown"
  return re.sub(r"[^A-Za-z0-9._-]+", "_", str(kind)).strip("_") or "unknown"


def config_root() -> Optional[str]:
  return (
    knobs.get_str(CONFIG_ENV)
    or knobs.get_str("IGNEOUS_COMPILE_CACHE")
    or None
  )


# [loaded?, config-or-None]: the tuned config is read at most once per
# process — knob resolution sits on hot paths (every page_shape() call)
_CONFIG: list = [False, None]


def tuned_config() -> Optional[dict]:
  """The active ``tuned/<device_kind>.json``; None when no config root
  is set, the file is absent, or it fails to parse — a bad tuned config
  must never take a worker down."""
  if _CONFIG[0]:
    return _CONFIG[1]
  cfg = None
  root = config_root()
  if root:
    try:
      from .storage import CloudFiles

      cfg = CloudFiles(root).get_json(f"{TUNED_PREFIX}{device_kind()}.json")
      if cfg is not None and not isinstance(cfg.get("knobs"), dict):
        cfg = None
    except Exception:
      cfg = None
  _CONFIG[0], _CONFIG[1] = True, cfg
  return cfg


def reset_cache() -> None:
  """Testing hook: forget the loaded tuned config."""
  _CONFIG[0], _CONFIG[1] = False, None


def resolve(name: str) -> Optional[str]:
  """Resolved string value of a tunable knob — explicit env > tuned
  config > None (the caller applies its registry default). Returns
  exactly what the env var would contain, so call sites keep their own
  strict parsing and error messages."""
  val = knobs.raw(name)
  if val:
    return val
  cfg = tuned_config()
  if cfg:
    tuned = cfg["knobs"].get(name)
    if tuned is not None:
      return str(tuned)
  return None


# ---------------------------------------------------------------------------
# generate-and-measure sweep


def candidates(backend: str) -> Dict[str, List[str]]:
  """Candidate values per tunable knob, per backend family. The empty
  string means "registry default" and is always swept first — it is the
  byte-identity reference AND the baseline the winner must beat."""
  if backend == "tpu":
    ccl = ["", "8,8,128", "8,16,128", "16,16,128", "8,16,256"]
  else:
    ccl = ["", "2,4,8", "4,8,8", "4,8,16", "8,16,16"]
  return {
    "IGNEOUS_CCL_TILE": ccl,
    "IGNEOUS_EDT_LINE_BLOCK": ["", "64", "128", "512"],
    "IGNEOUS_PAGE_SHAPE": ["", "16,16,16", "64,64,64"],
    "IGNEOUS_PAGE_BATCH": ["", "16", "64"],
  }


def _workloads(size: int) -> Dict[str, Callable[[], bytes]]:
  """Seeded representative workloads, one per knob; each returns the
  output bytes (the byte-identity oracle) and exercises the knob through
  its real resolution path. Executors are constructed INSIDE the call so
  each candidate resolves the knob fresh."""
  import numpy as np

  rng = np.random.default_rng(19)
  s = max(int(size), 16)

  ccl_batch = rng.integers(0, 5, (2, s, s, s)).astype(np.int32)

  def run_ccl() -> bytes:
    from .ops import ccl

    outs = ccl.connected_components_batch(
      ccl_batch, 6, executor=ccl._batch_executor(6)
    )
    return b"".join(np.asarray(o).tobytes() for o in outs)

  edt_batch_in = rng.integers(0, 3, (2, s, s, s)).astype(np.int32)

  def run_edt() -> bytes:
    from .ops import edt

    outs = edt.edt_batch(
      edt_batch_in, (1.0, 1.0, 1.0),
      executor=edt.batch_edt_executor((1.0, 1.0, 1.0)),
    )
    return b"".join(np.asarray(o).tobytes() for o in outs)

  ragged = [
    rng.integers(0, 255, (s, s - 7, s // 2 + 1)).astype(np.uint8),
    rng.integers(0, 255, (s // 2, s // 2, s // 2)).astype(np.uint8),
    rng.integers(0, 255, (s - 5, s // 3, 9)).astype(np.uint8),
  ]

  def run_paged() -> bytes:
    from .parallel import paged

    results = paged.paged_pyramid(ragged, (2, 2, 1), num_mips=2)
    return b"".join(
      np.asarray(m).tobytes() for mips in results for m in mips
    )

  return {
    "IGNEOUS_CCL_TILE": run_ccl,
    "IGNEOUS_EDT_LINE_BLOCK": run_edt,
    "IGNEOUS_PAGE_SHAPE": run_paged,
    "IGNEOUS_PAGE_BATCH": run_paged,
  }


class _env_pin:
  """Set one knob for the duration of a candidate measurement, restoring
  the previous state (including genuinely-unset) on exit."""

  def __init__(self, name: str, value: str):
    self.name, self.value = name, value

  def __enter__(self):
    self.prev = knobs.raw(self.name)
    if self.value:
      knobs.set_env(self.name, self.value)
    else:
      knobs.del_env(self.name)

  def __exit__(self, *exc):
    if self.prev is None:
      knobs.del_env(self.name)
    else:
      knobs.set_env(self.name, self.prev)


def run(
  out: Optional[str] = None,
  budget_sec: Optional[float] = None,
  repeats: Optional[int] = None,
  size: int = 48,
  only: Optional[List[str]] = None,
  strict: bool = True,
  log: Callable[[str], None] = lambda _msg: None,
) -> dict:
  """Sweep every tunable knob's candidates on this device kind and
  persist the winners.

  Per candidate: pin the env, run the workload once to warm the compile
  caches, then time ``repeats`` runs (best-of). Output bytes must equal
  the registry-default output — ``strict=True`` (the default) raises on
  any divergence, because these knobs are performance-only contracts.
  ``budget_sec`` bounds the whole sweep: when the deadline passes,
  remaining candidates are recorded as skipped and the defaults stand.

  Returns the tuned config dict (also written to
  ``<out or config root>/tuned/<device_kind>.json`` when resolvable).
  """
  import jax

  backend = jax.default_backend()
  repeats = max(
    int(repeats if repeats is not None
        else knobs.get_int("IGNEOUS_TUNE_REPEATS")), 1
  )
  if budget_sec is None:
    budget_sec = knobs.get_float("IGNEOUS_TUNE_BUDGET_SEC")
  deadline = (
    time.monotonic() + float(budget_sec) if budget_sec else None
  )
  cand = candidates(backend)
  work = _workloads(size)
  names = [n for n in TUNABLE if not only or n in only]

  winners: Dict[str, str] = {}
  measurements: Dict[str, list] = {}
  default_total = 0.0
  best_total = 0.0
  for name in names:
    fn = work[name]
    rows = []
    ref_bytes = None
    for value in cand[name]:
      if deadline is not None and time.monotonic() > deadline \
         and value != "":
        rows.append({"value": value, "skipped": "budget exhausted"})
        continue
      try:
        with _env_pin(name, value):
          got = fn()  # warmup: compiles land here, not in the timing
          best = None
          for _ in range(repeats):
            t0 = time.perf_counter()
            fn()
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
      except ValueError as exc:
        # an incompatible candidate (page/tile divisibility) is a skip,
        # not a failure — the geometry gates are doing their job
        rows.append({"value": value, "skipped": str(exc)})
        continue
      if value == "":
        ref_bytes = got
      identical = ref_bytes is not None and got == ref_bytes
      if not identical and strict:
        raise AssertionError(
          f"{name}={value!r} output diverged from the default path — "
          "tunables must be byte-identical; refusing to tune"
        )
      rows.append({
        "value": value, "seconds": round(best, 6), "identical": identical,
      })
      log(f"{name}={value or '<default>'}: {best:.4f}s"
          f"{'' if identical else ' (NOT byte-identical!)'}")
    measurements[name] = rows
    timed = [r for r in rows if r.get("identical")]
    default_row = next((r for r in rows if r["value"] == ""), None)
    if default_row is None or "seconds" not in default_row:
      continue
    winner = min(timed, key=lambda r: r["seconds"]) if timed \
      else default_row
    default_total += default_row["seconds"]
    best_total += min(winner["seconds"], default_row["seconds"])
    if winner["value"] and winner["seconds"] < default_row["seconds"]:
      winners[name] = winner["value"]
      log(f"{name}: tuned -> {winner['value']} "
          f"({default_row['seconds']:.4f}s -> {winner['seconds']:.4f}s)")

  config = {
    "version": 1,
    "device_kind": device_kind(),
    "backend": backend,
    "jax": str(jax.__version__),
    "created": time.time(),
    "knobs": winners,
    "measurements": measurements,
    "default_s": round(default_total, 6),
    "best_s": round(best_total, 6),
    "tune_best_vs_default_ratio": (
      round(best_total / default_total, 6) if default_total else None
    ),
  }
  root = out or config_root()
  if root:
    from .storage import CloudFiles

    CloudFiles(root).put_json(
      f"{TUNED_PREFIX}{device_kind()}.json", config
    )
    config["written_to"] = f"{root}/{TUNED_PREFIX}{device_kind()}.json"
  return config
