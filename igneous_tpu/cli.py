"""The ``igneous-tpu`` command line interface.

Command-tree AND option-level parity with the reference CLI
(/root/reference/igneous_cli/cli.py:185-214; audited programmatically —
every reference command and --option has a counterpart here):
  image {downsample, xfer, create, rm, roi, reorder,
         voxels {count,sum}, contrast {histogram,equalize,clahe},
         ccl {faces,links,calc-labels,relabel,clean,auto}}
  mesh {forge, merge, merge-sharded, xfer, rm, clean,
        spatial-index {create,db}}
  skeleton {forge, merge, merge-sharded, xfer, rm, clean, convert,
            spatial-index {create,db}}
  execute | queue {status,wait,release,rezero,purge,cp,mv,fsck,
                   dlq {ls,retry,purge}}
  fleet {status,trace,top,devices,compact,gc,check,watch}
  profile {capture,ls}
  design {ds-memory, ds-shape, bounds}
  view | license

Heavy imports (jax, task modules) happen inside commands so --help and
queue tooling stay instant.
"""

from __future__ import annotations

import os
import sys

import click

from .analysis import knobs


class Tuple3(click.ParamType):
  """'64,64,64' → (64, 64, 64) (reference cli.py:80-162 param types)."""

  name = "tuple3"

  def convert(self, value, param, ctx):
    if isinstance(value, (tuple, list)):
      return tuple(int(v) for v in value)
    try:
      parts = [int(v) for v in str(value).replace("x", ",").split(",")]
    except ValueError:
      self.fail(f"{value!r} is not an int triple like 64,64,64", param, ctx)
    if len(parts) != 3:
      self.fail(f"{value!r} must have exactly 3 components", param, ctx)
    return tuple(parts)


TUPLE3 = Tuple3()


class Tuple2(click.ParamType):
  """'0,1024' → (0, 1024) (reference cli.py:114-124)."""

  name = "tuple2"

  def convert(self, value, param, ctx):
    if isinstance(value, (tuple, list)):
      return tuple(int(v) for v in value)
    try:
      parts = [int(v) for v in str(value).split(",")]
    except ValueError:
      self.fail(f"{value!r} is not an int pair like 0,1024", param, ctx)
    if len(parts) != 2:
      self.fail(f"{value!r} must have exactly 2 components", param, ctx)
    return tuple(parts)


TUPLE2 = Tuple2()


def range_opts(fn):
  """Shared --xrange/--yrange/--zrange bounds restriction (reference
  cli.py:254-256 et al.)."""
  for opt in (
    click.option("--zrange", type=TUPLE2, default=None,
                 help="Restrict z-bounds (in the bounds mip), e.g. 0,1"),
    click.option("--yrange", type=TUPLE2, default=None,
                 help="Restrict y-bounds (in the bounds mip), e.g. 0,1024"),
    click.option("--xrange", type=TUPLE2, default=None,
                 help="Restrict x-bounds (in the bounds mip), e.g. 0,1024"),
  ):
    fn = opt(fn)
  return fn


def compute_cli_bounds(path, mip, xrange, yrange, zrange):
  """Bbox from the volume bounds at ``mip`` with any provided axis ranges
  overridden (reference cli.py:164-183); None when no range given."""
  if not (xrange or yrange or zrange):
    return None
  from .volume import Volume

  bounds = Volume(path).meta.bounds(mip or 0)
  for axis, rng in enumerate((xrange, yrange, zrange)):
    if rng:
      lo, hi = sorted(rng)
      bounds.minpt[axis] = lo
      bounds.maxpt[axis] = hi
  return bounds


def parse_id_list(value):
  """'5,6,7' → [5, 6, 7]; tolerant of blanks; None/empty → None."""
  if not value:
    return None
  try:
    ids = [int(tok) for tok in str(value).split(",") if tok.strip()]
  except ValueError:
    raise click.UsageError(f"not a comma-separated id list: {value!r}")
  return ids or None


def enqueue(queue_spec: str, tasks, parallel: int = 1):
  from .queues import LocalTaskQueue, TaskQueue

  if queue_spec is None:
    LocalTaskQueue(parallel=parallel).insert(tasks)
  else:
    # batched wire protocol (ISSUE 15): grid iterators know their task
    # count up front, which lets fq:// size its segment shards
    total = None
    if hasattr(tasks, "num_pending"):
      total = tasks.num_pending()
    elif hasattr(tasks, "__len__"):
      try:
        total = len(tasks)
      except TypeError:
        total = None
    TaskQueue(queue_spec).insert_batch(tasks, total=total)


@click.group()
@click.option("-p", "--parallel", default=1, show_default=True,
              help="Worker processes for local execution (0 = all cores).")
@click.version_option(version="0.3.0", prog_name="igneous-tpu")
@click.pass_context
def main(ctx, parallel):
  """igneous-tpu: TPU-native Neuroglancer Precomputed pipelines."""
  ctx.ensure_object(dict)
  # reference semantics: -p 0 means "use the number of cores"
  # (/root/reference/igneous_cli/cli.py:186)
  ctx.obj["parallel"] = parallel if parallel > 0 else (os.cpu_count() or 1)


# ---------------------------------------------------------------------------
# image


@main.group()
def image():
  """Downsample, transfer, ingest, delete image/segmentation layers."""


def _resolve_compress(compress, encoding):
  """'none'/'false' → False; lossy/self-compressed encodings skip the
  second-stage compressor (reference cli.py:283-287)."""
  if isinstance(compress, str) and compress.lower() in ("none", "false"):
    compress = False
  if encoding and str(encoding).lower() in ("jpeg", "jxl", "png", "fpzip",
                                            "zfpc"):
    compress = False
  return compress


@image.command("downsample")
@click.argument("path")
@click.option("--queue", "-q", default=None, help="fq:// queue (local if omitted)")
@click.option("--mip", default=0, show_default=True)
@click.option("--num-mips", default=5, show_default=True)
@click.option("--factor", type=TUPLE3, default=None, help="e.g. 2,2,1")
@click.option("--volumetric", is_flag=True, help="Use 2x2x2 downsampling.")
@click.option("--isotropic", is_flag=True,
              help="Per-mip factors driving the resolution toward isotropy.")
@click.option("--sparse", is_flag=True)
@click.option("--sharded", is_flag=True)
@click.option("--fill-missing", is_flag=True)
@click.option("--chunk-size", type=TUPLE3, default=None)
@click.option("--encoding", default=None)
@click.option("--encoding-level", type=int, default=None,
              help="png level / jpeg quality.")
@click.option("--encoding-effort", type=int, default=None,
              help="(jpeg xl) accepted for parity; jxl is not shipped.")
@click.option("--compress", default="gzip", show_default=True,
              help="Chunk compression: none, gzip, br.")
@click.option("--delete-bg", is_flag=True,
              help="Delete background tiles instead of uploading them.")
@click.option("--bg-color", default=0, show_default=True)
@click.option("--memory", "memory_target", default=int(3.5e9), show_default=True)
@click.option("--method", "downsample_method", default="auto", show_default=True)
@range_opts
@click.option("--batched", is_flag=True,
              help="Run on this host's device mesh now (K cutouts per "
                   "shard_map dispatch, double-buffered IO) instead of "
                   "enqueuing per-cutout tasks.")
@click.option("--batch-size", default=8, show_default=True,
              help="Cutouts per device dispatch with --batched.")
@click.option("--shape", type=TUPLE3, default=(256, 256, 64),
              show_default=True, help="Cutout shape with --batched.")
@click.pass_context
def image_downsample(ctx, path, queue, mip, num_mips, factor, volumetric,
                     isotropic, sparse, sharded, fill_missing, chunk_size,
                     encoding, encoding_level, encoding_effort, compress,
                     delete_bg, bg_color, memory_target, downsample_method,
                     xrange, yrange, zrange, batched, batch_size, shape):
  """Build the downsample pyramid of PATH."""
  from . import task_creation as tc

  if isotropic:
    if factor is not None or volumetric:
      raise click.UsageError("--isotropic excludes --factor/--volumetric")
    if sharded or batched:
      raise click.UsageError(
        "--isotropic plans per-mip factors, which only the unsharded "
        "task factory supports"
      )
    factor = "isotropic"
  elif volumetric:
    if factor is not None:
      raise click.UsageError("--volumetric and --factor are exclusive")
    factor = (2, 2, 2)
  compress = _resolve_compress(compress, encoding)
  bounds = compute_cli_bounds(path, mip, xrange, yrange, zrange)
  if batched:
    if sharded or queue:
      raise click.UsageError("--batched runs unsharded on this host (no -q)")
    if factor == "isotropic":
      raise click.UsageError("--batched uses one fixed --factor")
    if encoding or chunk_size:
      raise click.UsageError(
        "--batched downsamples in place; --encoding/--chunk-size apply "
        "only to the task factories"
      )
    from .parallel.batch_runner import batched_downsample

    stats = batched_downsample(
      path, mip=mip, num_mips=num_mips, shape=shape,
      batch_size=batch_size, factor=factor or (2, 2, 1), sparse=sparse,
      fill_missing=fill_missing, method=downsample_method, bounds=bounds,
    )
    click.echo(
      f"batched: {stats['batched_cutouts']} cutouts in "
      f"{stats['dispatches']} dispatches, {stats['edge_cutouts']} edge "
      f"cutouts via the task path"
    )
    return
  if sharded:
    tasks = tc.create_image_shard_downsample_tasks(
      path, mip=mip, fill_missing=fill_missing, sparse=sparse,
      chunk_size=chunk_size, encoding=encoding,
      encoding_level=encoding_level, encoding_effort=encoding_effort,
      factor=factor or (2, 2, 1), memory_target=memory_target,
      downsample_method=downsample_method, bounds=bounds, bounds_mip=mip,
      num_mips=num_mips,
    )
  else:
    tasks = tc.create_downsampling_tasks(
      path, mip=mip, num_mips=num_mips, fill_missing=fill_missing,
      sparse=sparse, chunk_size=chunk_size, encoding=encoding,
      encoding_level=encoding_level, encoding_effort=encoding_effort,
      delete_black_uploads=delete_bg, background_color=bg_color,
      compress=compress, factor=factor, memory_target=memory_target,
      downsample_method=downsample_method, bounds=bounds, bounds_mip=mip,
    )
  enqueue(queue, tasks, ctx.obj["parallel"])


@image.command("xfer")
@click.argument("src")
@click.argument("dest")
@click.option("--queue", "-q", default=None)
@click.option("--mip", default=0, show_default=True)
@click.option("--chunk-size", type=TUPLE3, default=None)
@click.option("--shape", type=TUPLE3, default=None,
              help="(overrides --memory) Task shape in voxels.")
@click.option("--translate", type=TUPLE3, default=(0, 0, 0))
@click.option("--fill-missing", is_flag=True)
@click.option("--sharded", is_flag=True)
@click.option("--encoding", default=None)
@click.option("--encoding-level", type=int, default=None,
              help="png level / jpeg quality.")
@click.option("--encoding-effort", type=int, default=None,
              help="(jpeg xl) accepted for parity; jxl is not shipped.")
@click.option("--compress", default="gzip", show_default=True,
              help="Chunk compression: none, gzip, br.")
@click.option("--downsample/--skip-downsample", "do_downsample", default=True,
              show_default=True,
              help="Produce downsamples from transfer tiles.")
@click.option("--max-mips", default=5, show_default=True,
              help="Maximum number of additional pyramid levels.")
@click.option("--num-mips", default=None, type=int,
              help="Deprecated alias for --max-mips.")
@click.option("--memory", "memory_target", default=int(3.5e9),
              show_default=True)
@click.option("--sparse", is_flag=True)
@click.option("--volumetric", is_flag=True, help="Use 2x2x2 downsampling.")
@click.option("--method", "--downsample-method", "downsample_method",
              default="auto", show_default=True)
@click.option("--delete-bg", is_flag=True)
@click.option("--bg-color", default=0, show_default=True)
@click.option("--dest-voxel-offset", type=TUPLE3, default=None,
              help="Set the new volume's global origin.")
@click.option("--clean-info", is_flag=True,
              help="Scrub mesh/skeleton fields from the new info.")
@click.option("--no-src-update", is_flag=True,
              help="Skip the source provenance note.")
@click.option("--truncate-scales/--no-truncate-scales", default=True,
              show_default=True,
              help="Drop source scales above --mip in the new info.")
@click.option("--use-https-src", is_flag=True,
              help="Parity flag: implies --no-src-update (no https "
                   "backend in this build).")
@range_opts
@click.option("--bounds-mip", default=None, type=int,
              help="Mip the ranges are specified in [default: --mip].")
@click.option("--cutout", is_flag=True,
              help="Restrict a newly created volume to the given bounds.")
@click.pass_context
def image_xfer(ctx, src, dest, queue, mip, chunk_size, shape, translate,
               fill_missing, sharded, encoding, encoding_level,
               encoding_effort, compress, do_downsample, max_mips, num_mips,
               memory_target, sparse, volumetric, downsample_method,
               delete_bg, bg_color, dest_voxel_offset, clean_info,
               no_src_update, truncate_scales, use_https_src, xrange, yrange,
               zrange, bounds_mip, cutout):
  """Transfer/rechunk/re-encode SRC into DEST."""
  from . import task_creation as tc

  compress = _resolve_compress(compress, encoding)
  bounds_mip = mip if bounds_mip is None else bounds_mip
  bounds = compute_cli_bounds(src, bounds_mip, xrange, yrange, zrange)
  factor = (2, 2, 2) if volumetric else None
  if num_mips is not None:
    max_mips = num_mips
  if not do_downsample:
    max_mips = 0
  if sharded:
    tasks = tc.create_image_shard_transfer_tasks(
      src, dest, mip=mip, chunk_size=chunk_size, encoding=encoding,
      encoding_level=encoding_level, encoding_effort=encoding_effort,
      translate=translate, fill_missing=fill_missing,
      dest_voxel_offset=dest_voxel_offset, bounds=bounds,
      bounds_mip=bounds_mip, uncompressed_shard_bytesize=memory_target,
      cutout=cutout, clean_info=clean_info, truncate_scales=truncate_scales,
    )
  else:
    tasks = tc.create_transfer_tasks(
      src, dest, chunk_size=chunk_size, shape=shape, mip=mip,
      translate=translate, fill_missing=fill_missing, encoding=encoding,
      encoding_level=encoding_level, encoding_effort=encoding_effort,
      compress=compress, num_mips=max_mips, memory_target=memory_target,
      sparse=sparse, factor=factor, downsample_method=downsample_method,
      delete_black_uploads=delete_bg, background_color=bg_color,
      dest_voxel_offset=dest_voxel_offset, clean_info=clean_info,
      no_src_update=no_src_update, truncate_scales=truncate_scales,
      use_https_for_source=use_https_src, bounds=bounds,
      bounds_mip=bounds_mip, cutout=cutout,
    )
  enqueue(queue, tasks, ctx.obj["parallel"])


@image.command("infer")
@click.argument("src")
@click.argument("dest")
@click.option("--model", "model_path", required=True,
              help="Cloudpath of a saved model (model.json + params.npz).")
@click.option("--queue", "-q", default=None)
@click.option("--mip", default=0, show_default=True)
@click.option("--shape", type=TUPLE3, default=None,
              help="Task shape in voxels (snapped up to chunk multiples).")
@click.option("--halo", type=TUPLE3, default=None,
              help="Context voxels per face [default: the model overlap].")
@click.option("--batch-size", default=4, show_default=True,
              help="Patches per device dispatch group.")
@click.option("--postprocess",
              type=click.Choice(["none", "quantize", "argmax"]),
              default="none", show_default=True,
              help="none: float32 channels; quantize: uint8 [0,1]*255; "
                   "argmax: uint8 channel argmax (segmentation).")
@click.option("--fill-missing", is_flag=True)
@click.option("--compress", default="gzip", show_default=True)
@click.option("--chunk-size", type=TUPLE3, default=None,
              help="Destination chunk size [default: source's].")
@range_opts
@click.option("--bounds-mip", default=None, type=int,
              help="Mip the ranges are specified in [default: --mip].")
@click.pass_context
def image_infer(ctx, src, dest, model_path, queue, mip, shape, halo,
                batch_size, postprocess, fill_missing, compress,
                chunk_size, xrange, yrange, zrange, bounds_mip):
  """Run conv-net inference over SRC into DEST (halo'd cutout →
  jitted JAX apply → overlap blend → Precomputed output)."""
  from . import task_creation as tc

  bounds_mip = mip if bounds_mip is None else bounds_mip
  bounds = compute_cli_bounds(src, bounds_mip, xrange, yrange, zrange)
  tasks = tc.create_inference_tasks(
    src, dest, model_path, mip=mip, shape=shape, halo=halo,
    bounds=bounds, bounds_mip=bounds_mip, fill_missing=fill_missing,
    batch_size=batch_size, postprocess=postprocess, compress=compress,
    chunk_size=chunk_size,
  )
  enqueue(queue, tasks, ctx.obj["parallel"])


@image.command("create")
@click.argument("src")
@click.argument("dest")
@click.option("--resolution", type=TUPLE3, default=(1, 1, 1), show_default=True)
@click.option("--offset", type=TUPLE3, default=(0, 0, 0), show_default=True)
@click.option("--chunk-size", type=TUPLE3, default=(64, 64, 64), show_default=True)
@click.option("--layer-type", default=None,
              type=click.Choice(["image", "segmentation"]))
@click.option("--seg", is_flag=True,
              help="Shorthand for --layer-type segmentation.")
@click.option("--encoding", default="raw", show_default=True)
@click.option("--encoding-level", type=int, default=None,
              help="png level / jpeg quality.")
@click.option("--encoding-effort", type=int, default=None,
              help="(jpeg xl) accepted for parity; jxl is not shipped.")
@click.option("--compress", default="gzip", show_default=True,
              help="Chunk compression: none, gzip, br.")
@click.option("--h5-dataset", default="main", show_default=True,
              help="Which h5 dataset to access (hdf5 imports only).")
def image_create(src, dest, resolution, offset, chunk_size, layer_type, seg,
                 encoding, encoding_level, encoding_effort, compress,
                 h5_dataset):
  """Ingest an array file (npy/npy.gz/h5/nrrd/nii/nii.gz) as a Precomputed
  layer (reference `igneous image create`, cli.py:1852-1923; ckl needs
  the crackle library and fails with instructions)."""
  from .formats import load_volume_file
  from .volume import Volume

  if seg:
    layer_type = "segmentation"
  try:
    arr = load_volume_file(src, h5_dataset=h5_dataset)
  except (ValueError, OSError) as e:  # OSError: corrupt gzip members
    raise click.UsageError(str(e))
  Volume.from_numpy(
    arr, dest, resolution=resolution, voxel_offset=offset,
    chunk_size=chunk_size, layer_type=layer_type, encoding=encoding,
    encoding_level=encoding_level,
    compress=_resolve_compress(compress, encoding),
  )
  click.echo(f"Created {dest} from {src} {arr.shape} {arr.dtype}")


@image.command("rm")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@click.option("--mip", default=0, show_default=True)
@click.option("--num-mips", default=0, show_default=True)
@click.option("--shape", type=TUPLE3, default=None,
              help="Task shape in voxels.")
@range_opts
@click.pass_context
def image_rm(ctx, path, queue, mip, num_mips, shape, xrange, yrange, zrange):
  """Delete image chunks at mip (… mip+num-mips)."""
  from . import task_creation as tc

  bounds = compute_cli_bounds(path, mip, xrange, yrange, zrange)
  enqueue(queue, tc.create_deletion_tasks(
    path, mip=mip, num_mips=num_mips, shape=shape, bounds=bounds,
    bounds_mip=mip,
  ), ctx.obj["parallel"])


# -- image contrast ----------------------------------------------------------


@image.group("contrast")
def image_contrast():
  """Luminance histograms, contrast stretch, CLAHE."""


@image_contrast.command("histogram")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@click.option("--mip", default=0, show_default=True)
@click.option("--coverage", default=0.01, show_default=True)
@click.option("--fill-missing", is_flag=True)
@range_opts
@click.option("--bounds-mip", default=None, type=int,
              help="Mip the ranges are specified in [default: --mip].")
@click.pass_context
def contrast_histogram(ctx, path, queue, mip, coverage, fill_missing,
                       xrange, yrange, zrange, bounds_mip):
  """Phase 1: per-z luminance histograms."""
  from . import task_creation as tc

  bounds_mip = mip if bounds_mip is None else bounds_mip
  bounds = compute_cli_bounds(path, bounds_mip, xrange, yrange, zrange)
  enqueue(queue, tc.create_luminance_levels_tasks(
    path, mip=mip, coverage_factor=coverage, fill_missing=fill_missing,
    bounds=bounds, bounds_mip=bounds_mip,
  ), ctx.obj["parallel"])


@image_contrast.command("equalize")
@click.argument("src")
@click.argument("dest")
@click.option("--queue", "-q", default=None)
@click.option("--mip", default=0, show_default=True)
@click.option("--clip-fraction", default=0.01, show_default=True)
@click.option("--shape", type=TUPLE3, default=None)
@click.option("--fill-missing", is_flag=True)
@click.option("--minval", type=int, default=0, show_default=True,
              help="Stretch floor value.")
@click.option("--maxval", type=int, default=255, show_default=True,
              help="Stretch ceiling value.")
@click.option("--translate", type=TUPLE3, default=(0, 0, 0))
@range_opts
@click.option("--bounds-mip", default=None, type=int,
              help="Mip the ranges are specified in [default: --mip].")
@click.pass_context
def contrast_equalize(ctx, src, dest, queue, mip, clip_fraction, shape,
                      fill_missing, minval, maxval, translate, xrange,
                      yrange, zrange, bounds_mip):
  """Phase 2: histogram stretch using phase-1 levels."""
  from . import task_creation as tc

  bounds_mip = mip if bounds_mip is None else bounds_mip
  bounds = compute_cli_bounds(src, bounds_mip, xrange, yrange, zrange)
  enqueue(queue, tc.create_contrast_normalization_tasks(
    src, dest, mip=mip, clip_fraction=clip_fraction, shape=shape,
    fill_missing=fill_missing, minval=minval, maxval=maxval,
    translate=translate, bounds=bounds, bounds_mip=bounds_mip,
  ), ctx.obj["parallel"])


class Tuple2Or1(click.ParamType):
  """'8' or '8,8' → (8, 8)."""

  name = "tuple2or1"

  def convert(self, value, param, ctx):
    if isinstance(value, (tuple, list)):
      return tuple(int(v) for v in value)
    try:
      parts = [int(v) for v in str(value).split(",")]
    except ValueError:
      self.fail(f"{value!r} is not like 8 or 8,8", param, ctx)
    if len(parts) == 1:
      parts = parts * 2
    if len(parts) != 2:
      self.fail(f"{value!r} must have 1 or 2 components", param, ctx)
    return tuple(parts)


@image_contrast.command("clahe")
@click.argument("src")
@click.argument("dest")
@click.option("--queue", "-q", default=None)
@click.option("--mip", default=0, show_default=True)
@click.option("--clip-limit", default=40.0, show_default=True)
@click.option("--tile-grid-size", "--tile-grid", "tile_grid",
              type=Tuple2Or1(), default=(8, 8), show_default=True,
              help="Size of the adaptive grid.")
@click.option("--shape", type=TUPLE3, default=(2048, 2048, 64), show_default=True)
@click.option("--fill-missing", is_flag=True)
@range_opts
@click.option("--bounds-mip", default=None, type=int,
              help="Mip the ranges are specified in [default: --mip].")
@click.pass_context
def contrast_clahe(ctx, src, dest, queue, mip, clip_limit, tile_grid, shape,
                   fill_missing, xrange, yrange, zrange, bounds_mip):
  from . import task_creation as tc

  bounds_mip = mip if bounds_mip is None else bounds_mip
  bounds = compute_cli_bounds(src, bounds_mip, xrange, yrange, zrange)
  enqueue(queue, tc.create_clahe_tasks(
    src, dest, mip=mip, clip_limit=clip_limit, tile_grid_size=tile_grid,
    shape=shape, fill_missing=fill_missing, bounds=bounds,
    bounds_mip=bounds_mip,
  ), ctx.obj["parallel"])


# -- image voxels ------------------------------------------------------------


@image.group("voxels")
def image_voxels():
  """Voxel statistics."""


@image_voxels.command("count")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@click.option("--mip", default=0, show_default=True)
@click.option("--shape", type=TUPLE3, default=(512, 512, 512), show_default=True)
@click.option("--fill-missing", is_flag=True)
@click.pass_context
def voxels_count(ctx, path, queue, mip, shape, fill_missing):
  """Census phase; run `voxels sum` afterwards."""
  from . import task_creation as tc

  enqueue(queue, tc.create_voxel_counting_tasks(
    path, mip=mip, shape=shape, fill_missing=fill_missing,
  ), ctx.obj["parallel"])


@image_voxels.command("sum")
@click.argument("path")
@click.option("--mip", default=0, show_default=True)
@click.option("--compress", default="gzip", show_default=True,
              help="Compression for the stored voxel_counts.im.")
@click.option("-o", "--output", default=None,
              help="Also write the IntMap file locally at this path.")
def voxels_sum(path, mip, compress, output):
  """Reduce census files into voxel_counts.im."""
  from . import task_creation as tc

  compress = _resolve_compress(compress, None) or None
  totals = tc.accumulate_voxel_counts(
    path, mip, compress=compress, additional_output=output,
  )
  click.echo(f"labels: {len(totals)}")


@image.command("roi")
@click.argument("path")
@click.option("--threshold", default=0.0, show_default=True)
@click.option("--dust", default=100, show_default=True,
              help="Suppress components smaller than this many voxels.")
@click.option("--suppress-faint", default=0, show_default=True,
              help="Voxels at or below this value become background.")
@click.option("--max-axial-len", default=512, show_default=True,
              help="Downsample in memory until XY fits this square.")
@click.option("--z-step", type=int, default=None,
              help="Evaluate ROIs per z-slab of this depth.")
@click.option("--progress", is_flag=True)
def image_roi(path, threshold, dust, suppress_faint, max_axial_len, z_step,
              progress):
  """Detect tissue regions of interest at the coarsest mip."""
  from . import task_creation as tc

  rois = tc.compute_rois(
    path, threshold=threshold, dust_threshold=dust,
    suppress_faint_voxels=suppress_faint, max_axial_length=max_axial_len,
    z_step=z_step, progress=progress,
  )
  for roi in rois:
    click.echo(str(roi))
  click.echo(f"{len(rois)} ROI detected. info file updated.")


@image.command("reorder")
@click.argument("src")
@click.argument("dest")
@click.argument("mapping_json", type=click.Path(exists=True), required=False)
@click.option("--mapping-file", type=click.Path(exists=True), default=None,
              help="JSON file of {dest_z: src_z} (reference flag form).")
@click.option("--queue", "-q", default=None)
@click.option("--mip", default=0, show_default=True)
@click.option("--fill-missing", is_flag=True)
@click.option("--encoding", default=None)
@click.option("--encoding-level", type=int, default=None)
@click.option("--encoding-effort", type=int, default=None,
              help="(jpeg xl) accepted for parity; jxl is not shipped.")
@click.option("--compress", default="gzip", show_default=True)
@click.option("--delete-bg", is_flag=True)
@click.option("--bg-color", default=0, show_default=True)
@click.pass_context
def image_reorder(ctx, src, dest, mapping_json, mapping_file, queue, mip,
                  fill_missing, encoding, encoding_level, encoding_effort,
                  compress, delete_bg, bg_color):
  """Shuffle z-slices per a {dest_z: src_z} JSON mapping."""
  import json as json_mod

  from . import task_creation as tc

  path = mapping_json or mapping_file
  if not path:
    raise click.UsageError("provide MAPPING_JSON or --mapping-file")
  with open(path) as f:
    mapping = json_mod.load(f)
  enqueue(queue, tc.create_reordering_tasks(
    src, dest, mapping, mip=mip, fill_missing=fill_missing,
    encoding=encoding, encoding_level=encoding_level,
    compress=_resolve_compress(compress, encoding),
    delete_black_uploads=delete_bg, background_color=bg_color,
  ), ctx.obj["parallel"])


# -- image ccl ---------------------------------------------------------------


@image.group("ccl")
def image_ccl():
  """Whole-image connected components labeling (4-pass)."""


_CCL_OPTS = [
  click.option("--mip", default=0, show_default=True),
  click.option("--shape", type=TUPLE3, default=(448, 448, 448), show_default=True),
  click.option("--threshold-gte", type=float, default=None),
  click.option("--threshold-lte", type=float, default=None),
  click.option("--fill-missing", is_flag=True),
  click.option("--dust", "dust_threshold", default=0, show_default=True,
               help="Delete objects smaller than this many voxels "
                    "within a cutout."),
]


def ccl_opts(fn):
  for opt in reversed(_CCL_OPTS):
    fn = opt(fn)
  return fn


@image_ccl.command("faces")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@ccl_opts
@click.pass_context
def ccl_faces(ctx, path, queue, mip, shape, threshold_gte, threshold_lte,
              fill_missing, dust_threshold):
  from . import task_creation as tc

  enqueue(queue, tc.create_ccl_face_tasks(
    path, mip, shape, fill_missing, threshold_gte, threshold_lte,
    dust_threshold=dust_threshold,
  ), ctx.obj["parallel"])


@image_ccl.command("links")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@ccl_opts
@click.pass_context
def ccl_links(ctx, path, queue, mip, shape, threshold_gte, threshold_lte,
              fill_missing, dust_threshold):
  from . import task_creation as tc

  enqueue(queue, tc.create_ccl_equivalence_tasks(
    path, mip, shape, fill_missing, threshold_gte, threshold_lte,
    dust_threshold=dust_threshold,
  ), ctx.obj["parallel"])


@image_ccl.command("calc-labels")
@click.argument("path")
@click.option("--mip", default=0, show_default=True)
@click.option("--shape", type=TUPLE3, default=(448, 448, 448),
              show_default=True,
              help="Accepted for parity; the stored equivalence files "
                   "already determine the task grid.")
def ccl_calc_labels(path, mip, shape):
  """Single-machine global union-find (pass 3)."""
  from . import task_creation as tc

  max_label = tc.create_relabeling(path, mip, shape)
  click.echo(f"max_label: {max_label}")


@image_ccl.command("relabel")
@click.argument("path")
@click.argument("dest")
@click.option("--queue", "-q", default=None)
@ccl_opts
@click.option("--encoding", default="compressed_segmentation", show_default=True)
@click.option("--chunk-size", type=TUPLE3, default=None,
              help="Chunk size of the destination layer.")
@click.pass_context
def ccl_relabel(ctx, path, dest, queue, mip, shape, threshold_gte,
                threshold_lte, fill_missing, dust_threshold, encoding,
                chunk_size):
  from . import task_creation as tc

  enqueue(queue, tc.create_ccl_relabel_tasks(
    path, dest, mip, shape, fill_missing, threshold_gte, threshold_lte,
    encoding=encoding, chunk_size=chunk_size, dust_threshold=dust_threshold,
  ), ctx.obj["parallel"])


@image_ccl.command("clean")
@click.argument("path")
@click.option("--mip", default=0, show_default=True)
def ccl_clean(path, mip):
  from . import task_creation as tc

  tc.clean_ccl_files(path, mip)


@image_ccl.command("auto")
@click.argument("path")
@click.argument("dest")
@click.option("--queue", "-q", default=None,
              help="Lease-based queue to drain each pass through "
                   "(local execution if omitted).")
@ccl_opts
@click.option("--encoding", default="compressed_segmentation", show_default=True)
@click.option("--chunk-size", type=TUPLE3, default=None,
              help="Chunk size of the destination layer.")
@click.option("--clean/--no-clean", default=True, show_default=True,
              help="Delete scratch files afterwards.")
@click.pass_context
def ccl_auto_cmd(ctx, path, dest, queue, mip, shape, threshold_gte,
                 threshold_lte, fill_missing, dust_threshold, encoding,
                 chunk_size, clean):
  """All four passes locally (reference cli.py:799-852)."""
  from . import task_creation as tc
  from .queues import LocalTaskQueue, TaskQueue

  tq = (
    TaskQueue(queue) if queue
    else LocalTaskQueue(parallel=ctx.obj["parallel"], progress=False)
  )
  max_label = tc.ccl_auto(
    path, dest, mip=mip, shape=shape, queue=tq,
    threshold_gte=threshold_gte, threshold_lte=threshold_lte,
    fill_missing=fill_missing, encoding=encoding, chunk_size=chunk_size,
    clean=clean, dust_threshold=dust_threshold,
  )
  click.echo(f"components: {max_label}")


# ---------------------------------------------------------------------------
# mesh


@main.group()
def mesh():
  """Mesh forging and management."""


@mesh.command("forge")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@click.option("--mip", default=0, show_default=True)
@click.option("--shape", type=TUPLE3, default=(448, 448, 448), show_default=True)
@click.option("--simplify/--skip-simplify", "simplify", default=True,
              show_default=True, help="Enable mesh simplification.")
@click.option("--simplify-factor", default=100, show_default=True)
@click.option("--max-error", default=40, show_default=True)
@click.option("--mesh-dir", "--dir", "mesh_dir", default=None,
              help="Write meshes into this directory instead of the one "
                   "in the info file.")
@click.option("--dust-threshold", "--dust", "dust_threshold", type=int,
              default=None,
              help="Skip objects smaller than this many voxels.")
@click.option("--dust-global/--dust-local", default=False, show_default=True,
              help="Dust by global voxel counts (requires a voxels census).")
@click.option("--fill-missing", is_flag=True)
@click.option("--fill-holes", type=int, default=0, show_default=True,
              help="0: off 1: fill cavities 2: also fix borders "
                   "3: also morphological closing.")
@click.option("--compress", default="gzip", show_default=True,
              help="Fragment file compression: none, gzip, br.")
@click.option("--sharded", is_flag=True)
@click.option("--spatial-index/--no-spatial-index", default=True, show_default=True)
@click.option("--closed-edge/--open-edge", "closed_edge", default=True,
              show_default=True,
              help="Close meshes against the dataset boundary.")
@click.option("--labels", "--obj-ids", "obj_ids", default=None,
              help="comma-separated: mesh only these labels")
@click.option("--exclude-labels", "--exclude-obj-ids", "exclude_obj_ids",
              default=None,
              help="comma-separated: never mesh these labels")
@click.option("--mesher", default="cubes", show_default=True,
              type=click.Choice(["cubes", "tetrahedra"]))
@click.option("--simplify-parallel", default=1, show_default=True,
              help="threads for per-label simplification inside each task")
@click.pass_context
def mesh_forge(ctx, path, queue, mip, shape, simplify, simplify_factor,
               max_error, mesh_dir, dust_threshold, dust_global,
               fill_missing, fill_holes, compress, sharded, spatial_index,
               closed_edge, obj_ids, exclude_obj_ids, mesher,
               simplify_parallel):
  from . import task_creation as tc

  compress = _resolve_compress(compress, None) or None
  enqueue(queue, tc.create_meshing_tasks(
    path, mip=mip, shape=shape,
    simplification=simplify,
    simplification_factor=simplify_factor,
    max_simplification_error=max_error,
    mesh_dir=mesh_dir, dust_threshold=dust_threshold,
    dust_global=dust_global,
    fill_missing=fill_missing, fill_holes=fill_holes, sharded=sharded,
    spatial_index=spatial_index,
    closed_dataset_edges=closed_edge,
    object_ids=parse_id_list(obj_ids),
    exclude_object_ids=parse_id_list(exclude_obj_ids),
    mesher=mesher, parallel=simplify_parallel, compress=compress,
  ), ctx.obj["parallel"])


@mesh.command("merge")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@click.option("--magnitude", default=2, show_default=True)
@click.option("--mesh-dir", "--dir", "mesh_dir", default=None)
@click.option("--nlod", default=0, show_default=True,
              help="(multires) Extra levels of detail; 0 = legacy "
                   "manifests.")
@click.option("--vqb", default=16, show_default=True,
              help="(multires) Vertex quantization bits: 10 or 16.")
@click.option("--min-chunk-size", type=TUPLE3, default=(256, 256, 256),
              show_default=True,
              help="(multires) Minimum finest-LOD fragment cell (voxels).")
@click.pass_context
def mesh_merge(ctx, path, queue, magnitude, mesh_dir, nlod, vqb,
               min_chunk_size):
  """Write legacy manifests — or unsharded multires with --nlod > 0
  (stage 2, reference cli.py:1073-1103)."""
  from . import task_creation as tc

  if nlod > 0:
    tasks = tc.create_unsharded_multires_mesh_tasks(
      path, magnitude=magnitude, mesh_dir=mesh_dir, num_lods=nlod + 1,
      vertex_quantization_bits=vqb, min_chunk_size=min_chunk_size,
    )
  else:
    tasks = tc.create_mesh_manifest_tasks(
      path, magnitude=magnitude, mesh_dir=mesh_dir)
  enqueue(queue, tasks, ctx.obj["parallel"])


@mesh.command("merge-sharded")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@click.option("--mesh-dir", "--dir", "mesh_dir", default=None)
@click.option("--num-lods", "num_lods", default=None, type=int,
              help="Total levels of detail [default: 2].")
@click.option("--nlod", default=None, type=int,
              help="Reference-style: EXTRA levels of detail "
                   "(total = nlod + 1).")
@click.option("--vqb", default=16, show_default=True,
              help="Vertex quantization bits: 10 or 16.")
@click.option("--min-chunk-size", type=TUPLE3, default=(256, 256, 256),
              show_default=True,
              help="Minimum finest-LOD fragment cell (voxels).")
@click.option("--compress-level", default=7, show_default=True,
              help="Draco compression level (recorded; this build's "
                   "encoder is fixed sequential-method).")
@click.option("--shard-index-bytes", default=2**13, show_default=True)
@click.option("--minishard-index-bytes", default=2**15, show_default=True)
@click.option("--minishard-index-encoding", default="gzip", show_default=True)
@click.option("--min-shards", default=1, show_default=True)
@click.option("--max-labels-per-shard", default=1000, show_default=True)
@click.option("--spatial-index-db", default=None,
              help="Query labels from this sqlite db (mesh spatial-index "
                   "db) instead of listing .spatial files.")
@click.pass_context
def mesh_merge_sharded(ctx, path, queue, mesh_dir, num_lods, nlod, vqb,
                       min_chunk_size, compress_level, shard_index_bytes,
                       minishard_index_bytes, minishard_index_encoding,
                       min_shards, max_labels_per_shard, spatial_index_db):
  """Sharded multires merge (reference cli.py:1105-1155)."""
  from . import task_creation as tc

  if num_lods is None:
    num_lods = (nlod + 1) if nlod is not None else 2
  enqueue(queue, tc.create_sharded_multires_mesh_tasks(
    path, mesh_dir=mesh_dir, num_lods=num_lods,
    vertex_quantization_bits=vqb, min_chunk_size=min_chunk_size,
    draco_compression_level=compress_level,
    shard_index_bytes=shard_index_bytes,
    minishard_index_bytes=minishard_index_bytes,
    minishard_index_encoding=minishard_index_encoding,
    min_shards=min_shards, max_labels_per_shard=max_labels_per_shard,
    spatial_index_db=spatial_index_db,
  ), ctx.obj["parallel"])


@mesh.group("spatial-index")
def mesh_spatial_index():
  """Mesh spatial-index maintenance."""


@mesh_spatial_index.command("create")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@click.option("--mip", default=0, show_default=True)
@click.option("--shape", type=TUPLE3, default=(448, 448, 448), show_default=True)
@click.option("--mesh-dir", default=None)
@click.option("--fill-missing", is_flag=True)
@click.pass_context
def mesh_spatial_index_create(ctx, path, queue, mip, shape, mesh_dir,
                              fill_missing):
  from . import task_creation as tc
  from .tasks.mesh import mesh_dir_for
  from .volume import Volume

  mdir = mesh_dir_for(Volume(path), mesh_dir)
  enqueue(queue, tc.create_spatial_index_tasks(
    path, mdir, mip=mip, shape=shape, fill_missing=fill_missing,
  ), ctx.obj["parallel"])


@mesh_spatial_index.command("db")
@click.argument("path")
@click.argument("db_path", type=click.Path())
@click.option("--mesh-dir", default=None)
@click.option("--progress", is_flag=True)
@click.option("--allow-missing", is_flag=True,
              help="Tolerate missing index files.")
def mesh_spatial_index_db(path, db_path, mesh_dir, progress, allow_missing):
  """Materialize the spatial index into a sqlite database."""
  from .spatial_index import SpatialIndex
  from .tasks.mesh import mesh_dir_for
  from .volume import Volume

  vol = Volume(path)
  mdir = mesh_dir_for(vol, mesh_dir)
  n = SpatialIndex(vol.cf, mdir).to_sqlite(
    db_path, progress=progress, allow_missing=allow_missing,
  )
  click.echo(f"wrote {n} rows to {db_path}")


@mesh.command("clean")
@click.argument("path")
@click.option("--mesh-dir", default=None)
def mesh_clean(path, mesh_dir):
  """Delete stage-1 intermediates (fragment files, .frags containers,
  .spatial cells), keeping manifests and multires outputs."""
  from .tasks.mesh import mesh_dir_for
  from .volume import Volume

  vol = Volume(path)
  mdir = mesh_dir_for(vol, mesh_dir)
  doomed = [
    k for k in vol.cf.list(f"{mdir}/")
    if k.endswith(".frags") or k.endswith(".spatial")
    or len(k.split("/")[-1].split(":")) == 3  # label:0:bbox fragments
  ]
  vol.cf.delete(doomed)
  click.echo(f"deleted {len(doomed)} intermediate files")


@mesh.command("xfer")
@click.argument("src")
@click.argument("dest")
@click.option("--queue", "-q", default=None)
@click.option("--mesh-dir", "--dir", "mesh_dir", default=None)
@click.option("--magnitude", default=1, show_default=True)
@click.option("--sharded", is_flag=True,
              help="Convert unsharded meshes to sharded multires at the "
                   "destination.")
@click.option("--nlod", default=0, show_default=True,
              help="(--sharded) Extra levels of detail.")
@click.option("--mip", type=int, default=None,
              help="Accepted for parity; the multires info records the "
                   "source mip.")
@click.pass_context
def mesh_xfer(ctx, src, dest, queue, mesh_dir, magnitude, sharded, nlod, mip):
  from . import task_creation as tc

  if sharded:
    tasks = tc.create_sharded_multires_mesh_from_unsharded_tasks(
      src, dest_cloudpath=dest, mesh_dir=mesh_dir, num_lods=nlod + 1,
    )
  else:
    tasks = tc.create_mesh_transfer_tasks(
      src, dest, mesh_dir=mesh_dir, magnitude=magnitude)
  enqueue(queue, tasks, ctx.obj["parallel"])


@mesh.command("rm")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@click.option("--mesh-dir", "--dir", "mesh_dir", default=None)
@click.option("--magnitude", default=1, show_default=True)
@click.pass_context
def mesh_rm(ctx, path, queue, mesh_dir, magnitude):
  from . import task_creation as tc

  enqueue(queue, tc.create_mesh_deletion_tasks(
    path, magnitude=magnitude, mesh_dir=mesh_dir), ctx.obj["parallel"])


# ---------------------------------------------------------------------------
# skeleton


@main.group()
def skeleton():
  """Skeleton forging and management."""


@skeleton.command("forge")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@click.option("--mip", default=0, show_default=True)
@click.option("--shape", type=TUPLE3, default=(512, 512, 512), show_default=True)
@click.option("--scale", default=4.0, show_default=True, help="TEASAR scale")
@click.option("--const", default=500.0, show_default=True, help="TEASAR const (nm)")
@click.option("--max-paths", type=float, default=None,
              help="Abort an object after tracing this many paths.")
@click.option("--dust-threshold", default=1000, show_default=True)
@click.option("--dust-global/--dust-local", default=False, show_default=True,
              help="dust by global voxel counts (requires a voxels census)")
@click.option("--fill-missing", is_flag=True)
@click.option("--fill-holes", type=int, default=0, show_default=True,
              help="0: off 1: fill cavities 2: +close box sides "
                   "3: +morphological closing")
@click.option("--sharded", is_flag=True)
@click.option("--skel-dir", default=None)
@click.option("--spatial-index/--skip-spatial-index", default=True,
              show_default=True)
@click.option("--fix-borders/--no-fix-borders", default=True, show_default=True)
@click.option("--fix-branching/--no-fix-branching", default=True,
              show_default=True,
              help="regrow the path field from the whole tree before each "
                   "branch so junctions attach on-center")
@click.option("--fix-avocados", is_flag=True,
              help="absorb nucleus labels engulfed by a soma and "
                   "re-EDT the solid cell body")
@click.option("--fix-autapses", is_flag=True,
              help="(graphene) constrain TEASAR to the chunk graph so "
                   "self-contacts are severed")
@click.option("--soma-detect", default=1100.0, show_default=True,
              help="soma candidate EDT threshold (physical units)")
@click.option("--soma-accept", default=3500.0, show_default=True,
              help="soma acceptance EDT threshold (physical units)")
@click.option("--soma-scale", default=2.0, show_default=True)
@click.option("--soma-const", default=300.0, show_default=True)
@click.option("--labels", default=None,
              help="comma-separated: skeletonize only these labels")
@click.option("--cross-section", type=int, default=0, show_default=True,
              help="Compute per-vertex cross sectional area; the value is "
                   "the normal-vector smoothing window (0 = off).")
@click.option("--cross-section-label-repair-sec", type=int, default=-1,
              show_default=True,
              help="Per-label time budget for contact repair: 0 off, "
                   "-1 unlimited.")
@click.option("--output", "-o", default=None,
              help="Write stage-1 fragments to this path instead.")
@click.option("--timestamp", type=int, default=None,
              help="(graphene) proofreading state at this UNIX time.")
@click.option("--root-ids", default=None,
              help="(graphene) materialized root-id layer to read instead "
                   "of querying the server.")
@click.option("--progress", is_flag=True,
              help="Accepted for parity; local queues already show a "
                   "progress bar.")
@click.pass_context
def skeleton_forge(ctx, path, queue, mip, shape, scale, const, max_paths,
                   dust_threshold, dust_global, fill_missing, fill_holes,
                   sharded, skel_dir, spatial_index, fix_borders,
                   fix_branching, fix_avocados, fix_autapses, soma_detect,
                   soma_accept, soma_scale, soma_const, labels,
                   cross_section, cross_section_label_repair_sec, output,
                   timestamp, root_ids, progress):
  from . import task_creation as tc

  enqueue(queue, tc.create_skeletonizing_tasks(
    path, mip=mip, shape=shape,
    teasar_params={
      "scale": scale, "const": const,
      "soma_detection_threshold": soma_detect,
      "soma_acceptance_threshold": soma_accept,
      "soma_invalidation_scale": soma_scale,
      "soma_invalidation_const": soma_const,
      "max_paths": max_paths,
    },
    dust_threshold=dust_threshold, dust_global=dust_global,
    fill_missing=fill_missing, fill_holes=fill_holes,
    sharded=sharded, skel_dir=skel_dir, spatial_index=spatial_index,
    fix_borders=fix_borders,
    fix_branching=fix_branching, fix_avocados=fix_avocados,
    fix_autapses=fix_autapses,
    object_ids=parse_id_list(labels),
    cross_sectional_area=(cross_section > 0),
    csa_smoothing_window=max(int(cross_section), 1),
    csa_repair_sec_per_label=cross_section_label_repair_sec,
    frag_path=output, timestamp=timestamp, root_ids_cloudpath=root_ids,
  ), ctx.obj["parallel"])


@skeleton.command("merge")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@click.option("--magnitude", default=1, show_default=True)
@click.option("--skel-dir", default=None)
@click.option("--dust-threshold", "--min-cable-length", "dust_threshold",
              default=4000.0, show_default=True,
              help="Skip objects shorter than this physical path length.")
@click.option("--tick-threshold", default=6000.0, show_default=True)
@click.option("--delete-fragments", is_flag=True)
@click.option("--max-cable-length", type=float, default=None,
              help="skip postprocessing (not upload) for merged skeletons "
                   "longer than this (nm) — bounds the cost of merge-error "
                   "monsters")
@click.pass_context
def skeleton_merge(ctx, path, queue, magnitude, skel_dir, dust_threshold,
                   tick_threshold, delete_fragments, max_cable_length):
  from . import task_creation as tc

  enqueue(queue, tc.create_unsharded_skeleton_merge_tasks(
    path, magnitude=magnitude, skel_dir=skel_dir,
    dust_threshold=dust_threshold, tick_threshold=tick_threshold,
    delete_fragments=delete_fragments, max_cable_length=max_cable_length,
  ), ctx.obj["parallel"])


@skeleton.command("merge-sharded")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@click.option("--skel-dir", default=None)
@click.option("--dust-threshold", "--min-cable-length", "dust_threshold",
              default=4000.0, show_default=True,
              help="Skip objects shorter than this physical path length.")
@click.option("--tick-threshold", default=6000.0, show_default=True)
@click.option("--max-cable-length", type=float, default=None,
              help="skip postprocessing for merged skeletons longer than "
                   "this (nm)")
@click.option("--shard-index-bytes", default=2**13, show_default=True)
@click.option("--minishard-index-bytes", default=2**15, show_default=True)
@click.option("--minishard-index-encoding", default="gzip", show_default=True)
@click.option("--data-encoding", default="gzip", show_default=True,
              help="Shard data compression: gzip or raw.")
@click.option("--min-shards", default=1, show_default=True)
@click.option("--max-labels-per-shard", default=2000, show_default=True)
@click.option("--spatial-index-db", default=None,
              help="Query labels from this sqlite db instead of listing "
                   ".spatial files.")
@click.pass_context
def skeleton_merge_sharded(ctx, path, queue, skel_dir, dust_threshold,
                           tick_threshold, max_cable_length,
                           shard_index_bytes, minishard_index_bytes,
                           minishard_index_encoding, data_encoding,
                           min_shards, max_labels_per_shard,
                           spatial_index_db):
  from . import task_creation as tc

  enqueue(queue, tc.create_sharded_skeleton_merge_tasks(
    path, skel_dir=skel_dir, dust_threshold=dust_threshold,
    tick_threshold=tick_threshold, max_cable_length=max_cable_length,
    shard_index_bytes=shard_index_bytes,
    minishard_index_bytes=minishard_index_bytes,
    minishard_index_encoding=minishard_index_encoding,
    data_encoding=data_encoding, min_shards=min_shards,
    max_labels_per_shard=max_labels_per_shard,
    spatial_index_db=spatial_index_db,
  ), ctx.obj["parallel"])


@skeleton.command("convert")
@click.argument("path")
@click.argument("out_dir", type=click.Path())
@click.option("--skel-dir", default=None)
@click.option("--labels", default=None, help="comma-separated label ids")
def skeleton_convert(path, out_dir, skel_dir, labels):
  """Export finished skeletons as SWC files
  (reference `igneous skeleton convert`)."""
  import os

  from .skeleton_io import Skeleton, to_swc
  from .tasks.skeleton import skel_dir_for
  from .volume import Volume

  vol = Volume(path)
  sdir = skel_dir_for(vol, skel_dir)
  attrs = (vol.cf.get_json(f"{sdir}/info") or {}).get("vertex_attributes")
  os.makedirs(out_dir, exist_ok=True)
  ids = parse_id_list(labels)
  wanted = set(ids) if ids else None
  n = 0
  for key in vol.cf.list(f"{sdir}/"):
    name = key.split("/")[-1]
    if not name.isdigit():
      continue
    label = int(name)
    if wanted is not None and label not in wanted:
      continue
    s = Skeleton.from_precomputed(vol.cf.get(key), vertex_attributes=attrs)
    with open(os.path.join(out_dir, f"{label}.swc"), "w") as f:
      f.write(to_swc(s, label=label))
    n += 1
  click.echo(f"wrote {n} swc files to {out_dir}")


@skeleton.group("spatial-index")
def skeleton_spatial_index():
  """Skeleton spatial-index maintenance."""


@skeleton_spatial_index.command("create")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@click.option("--mip", default=0, show_default=True)
@click.option("--shape", type=TUPLE3, default=(512, 512, 512), show_default=True)
@click.option("--skel-dir", default=None)
@click.option("--fill-missing", is_flag=True)
@click.pass_context
def skeleton_spatial_index_create(ctx, path, queue, mip, shape, skel_dir,
                                  fill_missing):
  """Rebuild the skeleton spatial index."""
  from . import task_creation as tc
  from .tasks.skeleton import skel_dir_for
  from .volume import Volume

  sdir = skel_dir_for(Volume(path), skel_dir)
  enqueue(queue, tc.create_spatial_index_tasks(
    path, sdir, mip=mip, shape=shape, fill_missing=fill_missing,
  ), ctx.obj["parallel"])


@skeleton_spatial_index.command("db")
@click.argument("path")
@click.argument("db_path", type=click.Path())
@click.option("--skel-dir", default=None)
@click.option("--progress", is_flag=True)
@click.option("--allow-missing", is_flag=True,
              help="Tolerate missing index files.")
def skeleton_spatial_index_db(path, db_path, skel_dir, progress,
                              allow_missing):
  """Materialize the skeleton spatial index into a sqlite database
  (reference `igneous skeleton spatial-index db`, cli.py:1565-1586)."""
  from .spatial_index import SpatialIndex
  from .tasks.skeleton import skel_dir_for
  from .volume import Volume

  vol = Volume(path)
  sdir = skel_dir_for(vol, skel_dir)
  n = SpatialIndex(vol.cf, sdir).to_sqlite(
    db_path, progress=progress, allow_missing=allow_missing,
  )
  click.echo(f"wrote {n} rows to {db_path}")


@skeleton.command("clean")
@click.argument("path")
@click.option("--skel-dir", default=None)
def skeleton_clean(path, skel_dir):
  """Delete stage-1 intermediates (.sk fragments, .frags containers,
  .spatial cells), keeping the merged skeletons."""
  from .tasks.skeleton import skel_dir_for
  from .volume import Volume

  vol = Volume(path)
  sdir = skel_dir_for(vol, skel_dir)
  doomed = [
    k for k in vol.cf.list(f"{sdir}/")
    if k.endswith(".sk") or k.endswith(".frags") or k.endswith(".spatial")
  ]
  vol.cf.delete(doomed)
  click.echo(f"deleted {len(doomed)} intermediate files")


@skeleton.command("xfer")
@click.argument("src")
@click.argument("dest")
@click.option("--queue", "-q", default=None)
@click.option("--skel-dir", "--dir", "skel_dir", default=None)
@click.option("--magnitude", default=1, show_default=True)
@click.option("--sharded", is_flag=True,
              help="Convert unsharded skeletons to sharded format at the "
                   "destination.")
@click.pass_context
def skeleton_xfer(ctx, src, dest, queue, skel_dir, magnitude, sharded):
  from . import task_creation as tc

  if sharded:
    enqueue(queue, tc.create_sharded_from_unsharded_skeleton_merge_tasks(
      src, dest_cloudpath=dest, skel_dir=skel_dir,
    ), ctx.obj["parallel"])
    return
  enqueue(queue, tc.create_skeleton_transfer_tasks(
    src, dest, skel_dir=skel_dir, magnitude=magnitude), ctx.obj["parallel"])


@skeleton.command("rm")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@click.option("--skel-dir", "--dir", "skel_dir", default=None)
@click.option("--magnitude", default=1, show_default=True)
@click.pass_context
def skeleton_rm(ctx, path, queue, skel_dir, magnitude):
  from . import task_creation as tc

  enqueue(queue, tc.create_skeleton_deletion_tasks(
    path, magnitude=magnitude, skel_dir=skel_dir), ctx.obj["parallel"])


# ---------------------------------------------------------------------------
# execute / queue / design


@main.command("execute")
@click.argument("queue_spec", required=False)
@click.option("--aws-region", default=None,
              help="AWS region of the SQS queue [default: $SQS_REGION_NAME].")
@click.option("--lease-sec", default=None, type=int,
              help="Visibility timeout [default: $LEASE_SECONDS or 600].")
@click.option("--tally/--no-tally", default=True, show_default=True,
              help="Tally completed fq:// tasks.")
@click.option("-n", "--num-tasks", "num_tasks", default=None, type=int,
              help="Stop after N tasks.")
@click.option("-x", "--exit-on-empty", is_flag=True)
@click.option("--min-sec", default=-1.0, show_default=True,
              help="Keep polling at least this long (<0: forever).")
@click.option("-q", "--quiet", is_flag=True,
              help="Suppress per-task status messages.")
@click.option("--time", "timing", is_flag=True,
              help="Log per-task wall time + stage breakdown as JSON lines.")
@click.option("--batch", "batch_size", default=1, show_default=True, type=int,
              help="Lease up to K compatible tasks per round and run their "
                   "device stage as ONE mesh dispatch (SURVEY §5.8). Each "
                   "lease still completes/recycles independently.")
@click.option("--max-deliveries", default=None, type=int,
              help="Quarantine a task in the queue's dlq/ after this many "
                   "deliveries instead of recycling it forever "
                   "[default: infinite retry].")
@click.option("--task-deadline", "task_deadline", default=None, type=float,
              help="Per-task wall-clock deadline in seconds; an overrun "
                   "counts as a failed delivery (recorded, then DLQ once "
                   "--max-deliveries is exhausted).")
@click.option("--heartbeat-sec", "heartbeat_sec", default=None, type=float,
              help="Renew held leases at this interval so long tasks "
                   "outlive a short --lease-sec without double execution "
                   "[default: $IGNEOUS_HEARTBEAT_SEC or lease/3; 0 "
                   "disables].")
@click.option("--drain-sentinel", default=None,
              help="Preemption watcher: drain gracefully (finish the "
                   "in-flight task, release the rest, exit 83) when this "
                   "file appears [default: $IGNEOUS_PREEMPT_SENTINEL; "
                   "SIGTERM/SIGINT and $IGNEOUS_PREEMPT_URL drain too].")
@click.option("--pipeline/--no-pipeline", "pipeline", default=None,
              help="Staged execution pipeline (ISSUE 3): thread each "
                   "task's chunk encode/uploads and prefetch batched "
                   "rounds' cutouts; byte-identical output, joined before "
                   "every lease delete [default: $IGNEOUS_PIPELINE].")
@click.option("--metrics-port", "metrics_port", default=None, type=int,
              help="Serve Prometheus text metrics on this port "
                   "(/metrics; 0 picks a free port) "
                   "[default: $IGNEOUS_METRICS_PORT; unset disables].")
@click.option("--journal", "journal_path", default=None,
              help="Where to append fleet journal segments (span batches "
                   "merged by `igneous fleet`) [default: $IGNEOUS_JOURNAL, "
                   "else <queue>/journal/ for fq:// queues].")
@click.pass_context
def execute(ctx, queue_spec, aws_region, lease_sec, tally, num_tasks,
            exit_on_empty, min_sec, quiet, timing, batch_size,
            max_deliveries, task_deadline, heartbeat_sec, drain_sentinel,
            pipeline, metrics_port, journal_path):
  """Worker poll loop: lease → run → delete
  (reference cli.py:888-964 semantics). QUEUE_SPEC falls back to the
  QUEUE_URL env var and --lease-sec to LEASE_SECONDS, so container CMDs
  stay declarative (secrets.py).

  Lifecycle: SIGTERM/SIGINT (or the preemption watcher) request a
  graceful drain — the in-flight task finishes, still-leased batch
  members are released, a final counters JSON line flushes, and the
  worker exits 83 so schedulers can tell "preempted" from "failed"."""
  import sys as sys_mod

  from . import lifecycle, secrets

  queue_spec = queue_spec or secrets.queue_url()
  if not queue_spec:
    raise click.UsageError("provide QUEUE_SPEC or set $QUEUE_URL")
  if lease_sec is None:
    lease_sec = secrets.lease_seconds()
  if aws_region:
    os.environ["SQS_REGION_NAME"] = aws_region
  if pipeline is not None:
    # env (not a param thread) so spawned workers inherit the choice
    knobs.set_env("IGNEOUS_PIPELINE", "1" if pipeline else "off")
  if journal_path is not None:
    knobs.set_env("IGNEOUS_JOURNAL", journal_path)  # children inherit too
  if metrics_port is not None:
    # multi-process workers each need their own port: 0 lets the OS pick
    knobs.set_env(
      "IGNEOUS_METRICS_PORT", 0 if ctx.obj["parallel"] > 1 else metrics_port
    )
  parallel = ctx.obj["parallel"]
  if parallel > 1:
    import multiprocessing as mp
    import time as time_mod

    # divide cores among workers for native kernel threading (same
    # oversubscription hygiene as the reference's cv2.setNumThreads(0))
    knobs.setdefault_env(
      "IGNEOUS_POOL_THREADS", max(1, (os.cpu_count() or 1) // parallel)
    )
    ctx_mp = mp.get_context("spawn")
    procs = [
      ctx_mp.Process(
        target=_execute_worker,
        args=(queue_spec, lease_sec, num_tasks, exit_on_empty, min_sec,
              timing, quiet, tally, batch_size, max_deliveries,
              task_deadline, heartbeat_sec, drain_sentinel),
      )
      for _ in range(parallel)
    ]
    for p in procs:
      p.start()
    # forward a drain request to every child (k8s signals pid 1 only);
    # each child runs its own graceful drain and exits 83
    flag = lifecycle.StopFlag()
    restore = lifecycle.install_signal_handlers(flag)
    try:
      while any(p.is_alive() for p in procs):
        if flag.is_set():
          for p in procs:
            if p.is_alive():
              p.terminate()  # SIGTERM → the child's own drain path
          break
        time_mod.sleep(0.2)
      for p in procs:
        p.join()
    finally:
      restore()
    if flag.is_set() or any(
      p.exitcode == lifecycle.EXIT_PREEMPTED for p in procs
    ):
      sys_mod.exit(lifecycle.EXIT_PREEMPTED)
    return
  _execute_worker(queue_spec, lease_sec, num_tasks, exit_on_empty, min_sec,
                  timing, quiet, tally, batch_size, max_deliveries,
                  task_deadline, heartbeat_sec, drain_sentinel)


def _execute_worker(queue_spec, lease_sec, num_tasks, exit_on_empty, min_sec,
                    timing=False, quiet=False, tally=True, batch_size=1,
                    max_deliveries=None, task_deadline=None,
                    heartbeat_sec=None, drain_sentinel=None):
  import sys as sys_mod
  import time

  import igneous_tpu.tasks  # noqa: F401  register all task classes
  from . import lifecycle, telemetry
  from .observability import journal as journal_mod
  from .observability import prom
  from .queues import TaskQueue

  flag = lifecycle.StopFlag()
  restore = lifecycle.install_signal_handlers(flag)
  watcher = lifecycle.PreemptionWatcher(flag, sentinel=drain_sentinel)
  watcher.start()

  tq = TaskQueue(queue_spec, max_deliveries=max_deliveries)

  # observability (ISSUE 5): journal segments + /metrics endpoint + an
  # atexit last-will so even a crashing worker leaves its final
  # counters line and span batch behind
  jpath = journal_mod.journal_path_for(tq, queue_spec)
  if jpath:
    journal_mod.set_active(journal_mod.Journal(jpath))
    # device telemetry plane (ISSUE 7): the utilization ledger rides
    # every journal flush and the profiler trigger poll rides the
    # between-tasks maybe_flush cadence
    from .observability import device as device_mod

    device_mod.install()
  journal_mod.install_last_will({"queue": queue_spec})
  # worker-liveness gauge (ISSUE 6): present while this process answers
  # scrapes; goes stale in Prometheus the moment the worker dies — the
  # health plane's per-worker "up" signal
  telemetry.gauge_set("worker.up", 1.0)
  bound_port = prom.start_http_server()
  if bound_port is not None and not quiet:
    click.echo(f"metrics: http://0.0.0.0:{bound_port}/metrics")

  start = time.time()

  def drained() -> bool:
    # "empty" only means nothing is leasable right now; with a delivery
    # budget the worker must outlive failed leases so every task ends
    # COMPLETED or DEAD-LETTERED, not stranded mid-recycle (the poison
    # task would otherwise need a second worker run to reach the DLQ)
    if max_deliveries is None:
      return True
    try:
      return tq.enqueued == 0
    except (NotImplementedError, AttributeError):
      return True

  def stop_fn(executed: int, empty: bool) -> bool:
    if num_tasks is not None and 0 <= num_tasks <= executed:
      return True
    if min_sec == 0 and (executed >= 1 or empty):
      # reference special value: run at most a single task (cli.py:892)
      return True
    if empty and exit_on_empty and drained():
      return True
    if empty and 0 <= min_sec <= (time.time() - start) and drained():
      return True
    return False

  try:
    if batch_size > 1:
      from .parallel.lease_batcher import poll_batched

      # honor --num-tasks / the min_sec==0 single-task special exactly:
      # the lease loop must not lease past the remaining budget
      task_budget = None
      if num_tasks is not None and num_tasks >= 0:
        task_budget = num_tasks
      if min_sec == 0:
        task_budget = 1 if task_budget is None else min(task_budget, 1)
      executed, stats = poll_batched(
        tq, batch_size=batch_size, lease_seconds=lease_sec,
        verbose=not quiet, stop_fn=stop_fn, task_budget=task_budget,
        timing=timing,  # per-ROUND JSON lines (tasks share dispatches)
        task_deadline_seconds=task_deadline,
        heartbeat_seconds=heartbeat_sec, drain_flag=flag,
      )
      if not quiet:
        click.echo(
          f"executed {executed} tasks "
          f"({stats['batched']} batched in "
          f"{sum(stats['dispatches'].values())} dispatches, "
          f"{stats['solo']} solo, {stats['failed']} failed, "
          f"{stats['released']} released)"
        )
    else:
      before_fn = after_fn = None
      if timing:
        from .telemetry import timed_poll_hooks

        before_fn, after_fn = timed_poll_hooks()

      executed = tq.poll(
        lease_seconds=lease_sec, verbose=not quiet, stop_fn=stop_fn,
        before_fn=before_fn, after_fn=after_fn, tally=tally,
        task_deadline_seconds=task_deadline,
        heartbeat_seconds=heartbeat_sec, drain_flag=flag,
      )
      if not quiet:
        click.echo(f"executed {executed} tasks")
  except BaseException:
    # crashing worker (satellite): the final counters line + journal
    # batch land NOW, with the real event name — not at teardown
    journal_mod.fire_last_will("crash", {"queue": queue_spec})
    raise
  finally:
    # write-envelope durability (ISSUE 16): buffered manifest records
    # land with the same urgency as the journal's last-will batch — an
    # audit must see digests for everything this worker uploaded
    from . import integrity as integrity_mod

    integrity_mod.flush_all(swallow=True)
    watcher.stop()
    restore()
  if flag.is_set():
    # last will: the counters line survives the pod for kubectl logs,
    # and the journal's final segment survives it in the bucket
    journal_mod.fire_last_will(
      "drain", {"reason": flag.reason, "executed": executed}
    )
    sys_mod.exit(lifecycle.EXIT_PREEMPTED)
  # clean exit: flush the journal without the counters line (stdout
  # contract unchanged for healthy drains)
  journal_mod.disarm_last_will()


# ---------------------------------------------------------------------------
# integrity audit (ISSUE 16)


def _audit_round(path, mips, report_dir, queue_spec, parallel,
                 check_digest, require_present, lease_sec, drain_sec):
  """One audit pass: fan the grid out, drain it, collect findings."""
  from . import integrity
  from .task_creation.audit import create_integrity_audit_tasks, load_findings

  integrity.flush_all()
  for mip in mips:
    tasks = create_integrity_audit_tasks(
      path, mip=mip, report_dir=report_dir,
      check_digest=check_digest, require_present=require_present,
    )
    enqueue(queue_spec, tasks, parallel)
  if queue_spec is not None:
    _drain_inline(queue_spec, lease_sec, drain_sec)
  return load_findings(report_dir)


def _drain_inline(queue_spec, lease_sec, deadline_sec):
  """Lease→execute→delete the queue to empty from this process (the
  audit CLI doubles as a worker so `--queue fq://…` needs no separate
  fleet; external workers leasing the same ranges just finish sooner)."""
  import time as time_mod

  from .queues import TaskQueue

  tq = TaskQueue(queue_spec)
  deadline = time_mod.monotonic() + deadline_sec

  def stop_fn(executed, empty):
    return (empty and tq.enqueued == 0) or time_mod.monotonic() > deadline

  tq.poll(lease_seconds=lease_sec, verbose=False, stop_fn=stop_fn,
          max_backoff_window=0.25)
  if tq.enqueued > 0:
    raise click.ClickException(
      f"audit queue failed to drain within {deadline_sec:.0f}s "
      f"({tq.enqueued} tasks left)"
    )


@main.command("audit")
@click.argument("path")
@click.option("--queue", "-q", "queue_spec", default=None,
              help="fq:// queue to fan the audit grid through (range "
                   "leases); runs locally if omitted.")
@click.option("--mip", "mips", multiple=True, type=int,
              help="Mip level(s) to audit. Default: every mip the "
                   "layer's recorded downsample campaign produced.")
@click.option("--report-dir", default=None,
              help="Findings/report location "
                   "[default: <path>/integrity/audit]")
@click.option("--out", default=None, type=click.Path(),
              help="Also write the completeness report JSON to this "
                   "local file.")
@click.option("--heal", is_flag=True,
              help="Re-enqueue the producing task for each damaged "
                   "cell and loop audit→repair→re-audit to convergence.")
@click.option("--max-rounds", default=5, show_default=True,
              help="Heal convergence bound.")
@click.option("--no-digest", is_flag=True,
              help="Skip manifest digest checks (presence+decode only).")
@click.option("--allow-missing", is_flag=True,
              help="Missing chunks are not findings (sparse campaigns "
                   "with delete_black_uploads).")
@click.option("--lease-sec", default=60.0, show_default=True)
@click.option("--drain-sec", default=600.0, show_default=True,
              help="Deadline for each queued round to drain.")
@click.pass_context
def audit(ctx, path, queue_spec, mips, report_dir, out, heal, max_rounds,
          no_digest, allow_missing, lease_sec, drain_sec):
  """Verify a campaign's outputs: presence, decode, manifest digests.

  Replays the expected chunk grid of PATH against the write envelope
  (ISSUE 16) and reports every missing, undecodable, or
  digest-mismatched chunk. Exit 0 = complete and intact; exit 2 =
  findings remain (each is named on stdout). With --heal, findings
  re-enqueue the producing DownsampleTask for exactly the damaged
  cells and the audit loops until clean or --max-rounds.
  """
  import json as json_mod
  import time as time_mod

  from . import chunk_cache, integrity
  from .observability import trace
  from .task_creation.audit import (
    downsample_provenance,
    downsample_repair_tasks,
  )
  from .volume import Volume

  parallel = ctx.obj["parallel"]
  path = path.rstrip("/")
  report_dir = report_dir or f"{path}/{integrity.INTEGRITY_PREFIX}/audit"
  vol = Volume(path, mip=0)
  prov = downsample_provenance(vol)
  if mips:
    mips = sorted(set(int(m) for m in mips))
  elif prov is not None:
    src = int(prov["mip"])
    mips = list(range(src + 1, src + int(prov["num_mips"]) + 1))
  else:
    raise click.ClickException(
      "no recorded downsample campaign in provenance: pass --mip "
      "explicitly to name the levels to audit"
    )

  findings, totals = _audit_round(
    path, mips, report_dir, queue_spec, parallel,
    not no_digest, not allow_missing, lease_sec, drain_sec,
  )
  rounds = 1
  repaired = 0
  while findings and heal and rounds <= max_rounds:
    tasks, unhealable = downsample_repair_tasks(path, findings, prov)
    if unhealable:
      for f in unhealable:
        click.echo(f"UNHEALABLE {f['kind']} mip={f['mip']} {f['key']}")
      break
    if not tasks:
      break
    click.echo(
      f"heal round {rounds}: {len(findings)} findings -> "
      f"{len(tasks)} repair tasks"
    )
    # repairs carry the audit's trace lineage through the queue, the
    # same way any enqueued campaign does
    with trace.activate(trace.SpanContext(trace.new_id(), None, True)):
      enqueue(queue_spec, tasks, parallel)
    if queue_spec is not None:
      _drain_inline(queue_spec, lease_sec, drain_sec)
    repaired += len(tasks)
    # repaired chunks re-enter reads fresh: drop any decoded chunks the
    # damaged bytes may have neighbored
    for mip in mips:
      chunk_cache.invalidate(path, mip)
    findings, totals = _audit_round(
      path, mips, report_dir, queue_spec, parallel,
      not no_digest, not allow_missing, lease_sec, drain_sec,
    )
    rounds += 1

  report = {
    "layer": path,
    "mips": list(mips),
    "rounds": rounds,
    "repair_tasks": repaired,
    "chunks_checked": totals["chunks"],
    "unmanifested": totals["unmanifested"],
    "findings": findings,
    "complete": not findings,
    "ts": time_mod.time(),
  }
  from .storage import CloudFiles

  CloudFiles(report_dir).put_json("report.json", report)
  if out:
    with open(out, "w") as f:
      json_mod.dump(report, f, indent=2, sort_keys=True)

  for f in findings:
    click.echo(f"CORRUPT {f['kind']} mip={f['mip']} {f['key']}")
  click.echo(
    f"audited {totals['chunks']} chunks across mips {list(mips)}: "
    + ("complete and intact"
       if not findings else f"{len(findings)} findings")
    + (f" ({repaired} repair tasks over {rounds} rounds)" if repaired else "")
  )
  if findings:
    raise SystemExit(2)


@main.group("queue")
def queue_group():
  """Queue inspection and maintenance (reference cli.py:1998-2054)."""


@queue_group.command("status")
@click.argument("queue_spec")
@click.option("--eta", is_flag=True, help="Sample throughput and estimate ETA.")
@click.option("--sample-sec", default=10.0, show_default=True,
              help="Live-sampling window for --eta; skipped entirely when "
                   "journal segments provide the throughput.")
def queue_status(queue_spec, eta, sample_sec):
  from .queues import TaskQueue

  tq = TaskQueue(queue_spec)
  click.echo(f"inserted: {tq.inserted}")
  click.echo(f"enqueued: {tq.enqueued}")
  click.echo(f"leased: {tq.leased}")
  click.echo(f"completed: {tq.completed}")
  if hasattr(tq, "queue_files"):
    # control-plane objects, not tasks: O(shards) for batch-inserted
    # campaigns — the scale-out signal (ISSUE 15)
    click.echo(f"queue files: {tq.queue_files}")
  if hasattr(tq, "dlq_count"):
    click.echo(f"dead-lettered: {tq.dlq_count}")
  if hasattr(tq, "stale_leases"):
    # zombie pressure: leases past expiry that no worker has recycled yet
    click.echo(f"stale leases: {tq.stale_leases}")
  if hasattr(tq, "lease_ages"):
    ages = tq.lease_ages()
    if ages:
      click.echo(f"lease_expiry_sec (min/max): {ages[0]:.0f}/{ages[-1]:.0f}")
  if eta:
    from .observability import journal as journal_mod
    from .telemetry import queue_eta

    # journal-derived throughput when the fleet left segments behind
    # (no sampling sleep); live two-sample estimate otherwise
    stats = queue_eta(
      tq, sample_seconds=sample_sec,
      journal_path=journal_mod.journal_path_for(tq, queue_spec),
    )
    click.echo(f"tasks/sec: {stats['tasks_per_sec']} ({stats['source']})")
    click.echo(f"eta_sec: {stats['eta_sec']}")


@queue_group.command("wait")
@click.argument("queue_spec")
@click.option("--interval", "--rate", "interval", default=5.0,
              show_default=True, help="seconds between checks")
@click.option("--timeout", default=None, type=float,
              help="give up after this many seconds")
@click.option("--aws-region", default=None,
              help="AWS region of the SQS queue.")
def queue_wait(queue_spec, interval, timeout, aws_region):
  """Block until the queue is empty (reference `igneous queue wait`,
  cli.py:1974). Uses the backend's own emptiness semantics — for sqs://
  that includes the eventual-consistency double-confirmation."""
  import time as _time

  from .queues import TaskQueue

  if aws_region:
    os.environ["SQS_REGION_NAME"] = aws_region
  q = TaskQueue(queue_spec)
  deadline = None if timeout is None else _time.monotonic() + timeout
  while True:
    if q.is_empty():
      click.echo("queue empty")
      return
    now = _time.monotonic()
    if deadline is not None and now >= deadline:
      raise click.ClickException(f"queue not empty after {timeout}s")
    # never sleep past the deadline (a long --interval must not make the
    # command overshoot --timeout)
    _time.sleep(interval if deadline is None else min(interval, deadline - now))


@queue_group.command("release")
@click.argument("queue_spec")
@click.option("--reset-deliveries", is_flag=True,
              help="Also zero delivery counts for tasks still in rotation "
                   "so a --max-deliveries budget starts fresh (re-arm "
                   "after a bad deploy burned deliveries on healthy "
                   "tasks). fq:// only; DLQ'd tasks keep their counts.")
def queue_release(queue_spec, reset_deliveries):
  """Drop all leases (crashed workers' tasks return immediately)."""
  from .queues import TaskQueue

  tq = TaskQueue(queue_spec)
  tq.release_all()
  if reset_deliveries:
    if not hasattr(tq, "reset_deliveries"):
      raise click.UsageError("--reset-deliveries supports fq:// queues only")
    n = tq.reset_deliveries()
    click.echo(f"reset delivery counts for {n} tasks")


@queue_group.command("purge")
@click.argument("queue_spec")
def queue_purge(queue_spec):
  from .queues import TaskQueue

  TaskQueue(queue_spec).purge()


@queue_group.command("rezero")
@click.argument("queue_spec")
def queue_rezero(queue_spec):
  from .queues import TaskQueue

  TaskQueue(queue_spec).rezero()


@queue_group.command("fsck")
@click.argument("queue_spec")
@click.option("--repair", is_flag=True,
              help="Quarantine malformed tasks, recycle bad leases.")
def queue_fsck(queue_spec, repair):
  """Audit queue consistency (malformed tasks, bad leases, counter drift)."""
  import json as json_mod

  from .queues import TaskQueue

  tq = TaskQueue(queue_spec)
  if not hasattr(tq, "fsck"):
    raise click.UsageError("fsck supports fq:// queues only")
  click.echo(json_mod.dumps(tq.fsck(repair=repair), indent=2))


@queue_group.group("dlq")
def dlq_group():
  """Dead-letter queue: inspect, requeue, or drop quarantined tasks.

  Tasks land here when a worker runs with --max-deliveries N and a task
  fails (raises, overruns its deadline, or loses its worker) on every
  delivery. fq:// queues only — SQS deployments use a RedrivePolicy."""


def _require_dlq(queue_spec):
  from .queues import TaskQueue

  tq = TaskQueue(queue_spec)
  if not hasattr(tq, "dlq_ls"):
    raise click.UsageError("queue dlq supports fq:// queues only")
  return tq


@dlq_group.command("ls")
@click.argument("queue_spec")
def dlq_ls(queue_spec):
  """One JSON line per quarantined task: payload, delivery count, and
  the recorded failure reasons (newest last)."""
  import json as json_mod

  for rec in _require_dlq(queue_spec).dlq_ls():
    click.echo(json_mod.dumps(rec))


@dlq_group.command("retry")
@click.argument("queue_spec")
@click.option("--name", "names", multiple=True,
              help="Specific task file(s); default: all.")
def dlq_retry(queue_spec, names):
  """Return quarantined tasks to rotation with a fresh delivery budget."""
  n = _require_dlq(queue_spec).dlq_retry(list(names) or None)
  click.echo(f"requeued {n} tasks")


@dlq_group.command("purge")
@click.argument("queue_spec")
def dlq_purge(queue_spec):
  """Drop all quarantined tasks. Irreversible."""
  n = _require_dlq(queue_spec).dlq_purge()
  click.echo(f"purged {n} tasks")


@queue_group.command("cp")
@click.argument("src")
@click.argument("dest")
def queue_cp(src, dest):
  """Copy pending tasks between queues."""
  from .queues import copy_queue

  click.echo(f"copied {copy_queue(src, dest)} tasks")


@queue_group.command("mv")
@click.argument("src")
@click.argument("dest")
def queue_mv(src, dest):
  """Move pending tasks between queues."""
  from .queues import move_queue

  click.echo(f"moved {move_queue(src, dest)} tasks")


# ---------------------------------------------------------------------------
# fleet observability (ISSUE 5)


@main.group("fleet")
def fleet_group():
  """Fleet observability: merge worker journal segments from the bucket.

  Workers running `igneous execute` append span/counter batches as JSONL
  segments under <queue>/journal/ (or $IGNEOUS_JOURNAL). These commands
  aggregate them AFTER the fact — no live connection to any worker."""


def _journal_location(queue_spec, journal_path):
  from .observability import journal as journal_mod
  from .queues import TaskQueue

  path = journal_path or knobs.get_str("IGNEOUS_JOURNAL")
  if path is None and queue_spec:
    path = journal_mod.journal_path_for(TaskQueue(queue_spec), queue_spec)
  if not path:
    raise click.UsageError(
      "no journal location: pass --journal, set $IGNEOUS_JOURNAL, or give "
      "an fq:// queue spec (whose journal/ sidecar is implied)"
    )
  return path


def _fleet_records(queue_spec, journal_path, effective=True):
  """Journal records for the fleet commands. ``effective`` reads rollup
  compactions + uncovered raw segments (O(windows) — status/top/check);
  ``effective=False`` reads every raw segment (`fleet trace` needs the
  per-span detail rollups summarize away). Byte-compatible: with no
  rollups present the two views are identical."""
  from .observability import fleet

  path = _journal_location(queue_spec, journal_path)
  records = fleet.load_effective(path) if effective else fleet.load(path)
  if not records:
    raise click.ClickException(f"no journal segments under {path}")
  return records


def _queue_depth_stats(queue_spec):
  """Best-effort depth snapshot for the health engine (None without a
  queue spec — health still runs, minus backlog-driven detectors)."""
  if not queue_spec:
    return None
  from .queues import TaskQueue

  try:
    tq = TaskQueue(queue_spec)
  except Exception as e:
    raise click.UsageError(f"cannot open queue {queue_spec}: {e}")
  if hasattr(tq, "depth_snapshot"):
    return tq.depth_snapshot()
  return {"backlog": getattr(tq, "backlog", None) or tq.enqueued}


def _journal_opts(fn):
  for opt in (
    click.option("--journal", "journal_path", default=None,
                 help="Journal path override [default: $IGNEOUS_JOURNAL or "
                      "<queue>/journal/]."),
    click.option("--queue", "-q", "queue_spec", default=None,
                 help="Queue whose journal/ sidecar to read "
                      "[default: $QUEUE_URL]."),
  ):
    fn = opt(fn)
  return fn


@fleet_group.command("status")
@_journal_opts
@click.option("--json", "as_json", is_flag=True, help="Machine-readable.")
def fleet_status(queue_spec, journal_path, as_json):
  """Per-stage fleet aggregates: p50/p95 stage times, stall ratio,
  throughput, zombie/DLQ tallies — merged across every worker."""
  import json as json_mod

  from . import secrets
  from .observability import fleet

  st = fleet.status(_fleet_records(queue_spec or secrets.queue_url(),
                                   journal_path))
  if as_json:
    click.echo(json_mod.dumps(st, indent=2))
    return
  click.echo(f"workers: {len(st['workers'])} ({', '.join(st['workers'])})")
  click.echo(f"window: {st['window_sec']}s")
  click.echo(
    f"tasks: {st['tasks']} ({st['tasks_failed']} failed spans, "
    f"{st['tasks_failed_counter']} failure counters)"
  )
  if st["tasks_per_sec"] is not None:
    click.echo(f"tasks/sec: {st['tasks_per_sec']}")
  if st["stall_ratio"] is not None:
    click.echo(f"stall ratio: {st['stall_ratio']}")
  click.echo(f"zombie fences: {st['zombie_fences']}  "
             f"dlq promoted: {st['dlq_promoted']}")
  surv = {
    k: v for k, v in st["counters"].items()
    if k.startswith(("speculation.", "steal."))
  }
  if surv:
    click.echo("campaign survival: " + "  ".join(
      f"{k.split('.', 1)[1] if k.startswith('speculation.') else k} {v}"
      for k, v in sorted(surv.items())
    ))
  click.echo("stage                                count   total_s  "
             "p50_ms   p95_ms")
  for name, s in st["stages"].items():
    click.echo(
      f"{name:<36} {s['count']:>6} {s['total_s']:>9} "
      f"{s['p50_ms']:>8} {s['p95_ms']:>8}"
    )


@fleet_group.command("trace")
@click.argument("trace_id")
@_journal_opts
@click.option("-o", "--out", "out_path", default=None,
              help="Also write a Perfetto/Chrome trace JSON here "
                   "(open at ui.perfetto.dev).")
def fleet_trace(trace_id, queue_spec, journal_path, out_path):
  """One task's merged lineage: enqueue wait, every delivery (retries
  included), and the pipeline stage spans inside each, across workers."""
  from . import secrets
  from .observability import fleet, perfetto

  records = _fleet_records(queue_spec or secrets.queue_url(), journal_path,
                           effective=False)
  spans = fleet.trace_records(records, trace_id)
  if not spans:
    raise click.ClickException(f"no spans recorded for trace {trace_id}")
  for line in fleet.render_trace(spans):
    click.echo(line)
  if out_path:
    n = perfetto.dump(records, out_path, trace_id=trace_id)
    click.echo(f"wrote {n} events to {out_path}")


@fleet_group.command("top")
@_journal_opts
@click.option("-n", "top_n", default=10, show_default=True)
def fleet_top(queue_spec, journal_path, top_n):
  """Slowest task executions by trace (feed one to `fleet trace`)."""
  from . import secrets
  from .observability import fleet

  records = _fleet_records(queue_spec or secrets.queue_url(), journal_path)
  rows = fleet.slowest_tasks(records, n=top_n)
  if not rows:
    raise click.ClickException("no task spans in the journal")
  click.echo("dur_s     task                       attempt  trace_id")
  for r in rows:
    err = f"  ERROR={r['error']}" if r.get("error") else ""
    click.echo(
      f"{r['dur_s']:>8.3f}  {r['task']:<25} {str(r['attempt'] or '-'):>7}"
      f"  {r['trace_id']}  @{r['worker']}{err}"
    )


@fleet_group.command("devices")
@_journal_opts
@click.option("--json", "as_json", is_flag=True, help="Machine-readable.")
def fleet_devices(queue_spec, journal_path, as_json):
  """Merged per-device utilization table (ISSUE 7): busy seconds/ratio,
  dispatches, recompiles, HBM peak per worker x device, per-kernel
  vox/s, and the batched-vs-host fast-path tally — from the cumulative
  device ledgers each worker flushes into the journal."""
  from . import secrets
  from .observability import device as device_mod

  records = _fleet_records(queue_spec or secrets.queue_url(), journal_path)
  ledgers = device_mod.device_ledgers(records)
  if as_json:
    click.echo(device_mod.report_json(ledgers))
    return
  for line in device_mod.render_devices(ledgers):
    click.echo(line)


@fleet_group.command("compact")
@_journal_opts
@click.option("--window-sec", "window", default=None, type=float,
              help="Rollup window width [default: $IGNEOUS_ROLLUP_WINDOW_SEC "
                   "or 60].")
@click.option("--min-segments", default=2, show_default=True, type=int,
              help="Skip when fewer uncovered raw segments exist.")
def fleet_compact(queue_spec, journal_path, window, min_segments):
  """Fold raw journal segments into windowed rollups (ISSUE 6).

  After compaction, `fleet status|top|check|watch` and `queue status
  --eta` read O(windows) instead of O(all segments), and the covered raw
  segments become GC-able via `fleet gc`. Workers self-compact their own
  segments every $IGNEOUS_ROLLUP_EVERY flushes; this command is the
  admin/cron sweep for whatever they left behind."""
  import json as json_mod

  from . import secrets
  from .observability import rollup

  path = _journal_location(queue_spec or secrets.queue_url(), journal_path)
  res = rollup.compact(path, window=window, min_segments=min_segments)
  click.echo(json_mod.dumps(res))


@fleet_group.command("gc")
@_journal_opts
@click.option("--retain-sec", default=None, type=float,
              help="Keep covered raw segments at least this long "
                   "[default: $IGNEOUS_JOURNAL_RETAIN or 3600]. This is "
                   "the `fleet trace` debuggability horizon: rollups keep "
                   "aggregates forever, per-span detail only lives in "
                   "raw segments.")
def fleet_gc(queue_spec, journal_path, retain_sec):
  """Delete raw journal segments already folded into rollups."""
  import json as json_mod

  from . import secrets
  from .observability import rollup

  path = _journal_location(queue_spec or secrets.queue_url(), journal_path)
  click.echo(json_mod.dumps(rollup.gc(path, retain=retain_sec)))


def _health_opts(fn):
  for opt in (
    click.option("--window-sec", "window_sec", default=None, type=float,
                 help="Analysis window [default: $IGNEOUS_HEALTH_WINDOW_SEC "
                      "or 600]."),
    click.option("--stall-sec", "stall_sec", default=None, type=float,
                 help="Flag a worker whose journal went silent this long "
                      "with backlog remaining [default: "
                      "$IGNEOUS_HEALTH_STALL_SEC or 120]."),
    click.option("--straggler-ratio", "straggler_ratio", default=None,
                 type=float,
                 help="Flag a worker at p95 >= ratio x fleet median "
                      "[default: $IGNEOUS_HEALTH_STRAGGLER_RATIO or 3]."),
    click.option("--horizon-sec", "horizon_sec", default=None, type=float,
                 help="Autoscaler target: drain the backlog within this "
                      "many seconds [default: $IGNEOUS_AUTOSCALE_HORIZON_SEC "
                      "or 600]."),
  ):
    fn = opt(fn)
  return fn


def _evaluate_health(queue_spec, journal_path, window_sec, stall_sec,
                     straggler_ratio, horizon_sec):
  from .observability import fleet, health

  path = _journal_location(queue_spec, journal_path)
  records = fleet.load_effective(path)
  if not records:
    raise click.ClickException(f"no journal segments under {path}")
  cfg = health.HealthConfig.from_env(
    window_sec=window_sec, stall_sec=stall_sec,
    straggler_ratio=straggler_ratio, horizon_sec=horizon_sec,
  )
  queue_stats = _queue_depth_stats(queue_spec)
  report = health.HealthEngine(cfg).evaluate(records, queue_stats)
  return path, report, queue_stats


@fleet_group.command("check")
@_journal_opts
@_health_opts
@click.option("--json", "as_json", is_flag=True, help="Machine-readable.")
@click.option("--out", "out_path", default=None,
              help="Also write the full report JSON here (CI artifact).")
@click.option("--emit-events/--no-emit-events", default=True,
              show_default=True,
              help="Append structured health.* events to the journal.")
@click.option("--flags/--no-flags", "write_flags", default=True,
              show_default=True,
              help="Publish <journal>/health/flags.json so flagged "
                   "workers surrender pre-leases (LeaseBatcher polls it).")
@click.option("--textfile", default=None,
              help="Write the Prometheus textfile (incl. "
                   "igneous_fleet_desired_workers / igneous_slo_burn / "
                   "igneous_fleet_stragglers) here for the node-exporter "
                   "collector [default: $IGNEOUS_METRICS_TEXTFILE].")
def fleet_check(queue_spec, journal_path, window_sec, stall_sec,
                straggler_ratio, horizon_sec, as_json, out_path,
                emit_events, write_flags, textfile):
  """One health evaluation, exit-code-bearing (CI/cron gate).

  Exit 0 = healthy; exit 2 = stragglers/anomalies/SLO burn detected —
  the output names each one. Also publishes the autoscaler signal and
  straggler flags unless told otherwise."""
  import json as json_mod
  import sys as sys_mod

  from . import secrets
  from .observability import health, journal as journal_mod, prom

  path, report, _ = _evaluate_health(
    queue_spec or secrets.queue_url(), journal_path,
    window_sec, stall_sec, straggler_ratio, horizon_sec,
  )
  health.publish_gauges(report)
  if textfile or knobs.get_str("IGNEOUS_METRICS_TEXTFILE"):
    prom.write_textfile(textfile)
  if emit_events:
    health.emit_events(
      report,
      journal_mod.Journal(path, worker_id=health.default_checker_id()),
    )
  if write_flags:
    health.write_flags(path, report)
  if out_path:
    with open(out_path, "w") as f:
      f.write(health.report_json(report))
  if as_json:
    click.echo(health.report_json(report))
  else:
    for line in health.check_lines(report):
      click.echo(line)
  if not report["healthy"]:
    sys_mod.exit(2)


@fleet_group.command("watch")
@_journal_opts
@_health_opts
@click.option("--interval", default=5.0, show_default=True,
              help="Seconds between refreshes.")
@click.option("--iterations", default=None, type=int,
              help="Render N frames then exit [default: until Ctrl-C].")
@click.option("--no-clear", is_flag=True,
              help="Append frames instead of redrawing in place.")
@click.option("--once", is_flag=True,
              help="Render exactly one frame and exit (same as "
                   "--iterations 1).")
@click.option("--json", "as_json", is_flag=True,
              help="Emit each frame as one JSON object (report + queue "
                   "snapshot) instead of the ANSI dashboard — for "
                   "dashboards and the simulator's live-vs-predicted "
                   "comparison. Implies --no-clear.")
def fleet_watch(queue_spec, journal_path, window_sec, stall_sec,
                straggler_ratio, horizon_sec, interval, iterations,
                no_clear, once, as_json):
  """Live fleet dashboard over the journal rollups: status, per-worker
  table, stragglers, alerts, autoscale — refreshed in place."""
  import json as json_mod
  import time as time_mod

  from . import secrets
  from .observability import health

  queue_spec = queue_spec or secrets.queue_url()
  if once:
    iterations = 1
  n = 0
  while True:
    report = queue_stats = None
    try:
      _path, report, queue_stats = _evaluate_health(
        queue_spec, journal_path,
        window_sec, stall_sec, straggler_ratio, horizon_sec,
      )
      lines = health.render_dashboard(report, queue_stats)
    except click.ClickException as e:
      lines = [f"fleet watch: {e.message} (waiting...)"]
    if as_json:
      click.echo(json_mod.dumps({
        "report": report, "queue": queue_stats,
        "error": None if report is not None else lines[0],
      }))
    else:
      if not no_clear:
        click.echo("\x1b[2J\x1b[H", nl=False)
      for line in lines:
        click.echo(line)
    n += 1
    if iterations is not None and n >= iterations:
      return
    time_mod.sleep(max(interval, 0.0))


def _parse_kv_spec(spec, caster=float):
  out = {}
  for part in (spec or "").split(","):
    part = part.strip()
    if not part:
      continue
    if "=" not in part:
      raise click.UsageError(f"expected key=value, got {part!r}")
    k, v = part.split("=", 1)
    try:
      out[k.strip()] = caster(v)
    except ValueError:
      raise click.UsageError(f"bad value in {part!r}")
  return out


def _load_or_mine_model(mine_path, model_path, window_sec=None):
  from .observability import replay

  if model_path:
    import json as json_mod

    with open(model_path) as f:
      return replay.WorkloadModel.from_dict(json_mod.load(f))
  model = replay.mine_journal(mine_path, window_sec=window_sec)
  if not model.task_types:
    raise click.ClickException(
      f"no task spans to mine under {mine_path} — run a journaled "
      "campaign first, or pass --model"
    )
  return model


def _autoscale_policy_opts(fn):
  for opt in (
    click.option("--step-max", default=None, type=int,
                 help="Max workers added/removed per action "
                      "[default: $IGNEOUS_AUTOSCALE_STEP_MAX or uncapped]."),
    click.option("--cooldown-sec", default=None, type=float,
                 help="Min seconds between scale actions "
                      "[default: $IGNEOUS_AUTOSCALE_COOLDOWN_SEC or 60]."),
    click.option("--hysteresis", default=None, type=float,
                 help="Dead band around current size "
                      "[default: $IGNEOUS_AUTOSCALE_HYSTERESIS or 0.2]."),
    click.option("--horizon-sec", "as_horizon_sec", default=None, type=float,
                 help="Drain the backlog within this many seconds "
                      "[default: $IGNEOUS_AUTOSCALE_HORIZON_SEC or 600]."),
    click.option("--max-workers", default=None, type=int,
                 help="Fleet ceiling [default: $IGNEOUS_AUTOSCALE_MAX "
                      "or 1000]."),
    click.option("--min-workers", default=None, type=int,
                 help="Fleet floor [default: $IGNEOUS_AUTOSCALE_MIN or 1]."),
  ):
    fn = opt(fn)
  return fn


def _policy_from_opts(min_workers, max_workers, as_horizon_sec, hysteresis,
                      cooldown_sec, step_max):
  from .observability import autoscale

  return autoscale.AutoscalePolicy.from_env(
    min_workers=min_workers, max_workers=max_workers,
    horizon_sec=as_horizon_sec, hysteresis=hysteresis,
    cooldown_sec=cooldown_sec, step_max=step_max,
  )


@fleet_group.command("simulate")
@_journal_opts
@click.option("--from-journal", "mine_path", default=None,
              help="Journal to mine the workload model from [default: the "
                   "--journal/--queue location].")
@click.option("--model", "model_path", default=None,
              help="Load a saved workload_model.json instead of mining.")
@click.option("--save-model", "save_model_path", default=None,
              help="Write the mined model JSON here (commit it, diff it, "
                   "re-simulate it months later).")
@click.option("--workers", default=4, show_default=True, type=int)
@click.option("--tasks", default=None, type=int,
              help="Scale the campaign to N total tasks (mined mix "
                   "proportions kept) [default: replay the mined counts].")
@click.option("--seed", default=0, show_default=True, type=int)
@click.option("--batch-size", default=None, type=int,
              help="Members per lease round [default: $IGNEOUS_SIM_BATCH "
                   "or 1].")
@click.option("--fail-scale", default=None, type=float,
              help="Multiply mined failure probabilities (what-if on "
                   "fault rates) [default: $IGNEOUS_SIM_FAIL_SCALE or 1].")
@click.option("--policy", "policy_mode",
              type=click.Choice(["fixed", "auto"]), default="fixed",
              show_default=True,
              help="fixed = N workers for the whole run; auto = a virtual "
                   "autoscale controller (the SAME PolicyLoop `fleet "
                   "autoscale` runs) sizes the fleet as it goes.")
@_autoscale_policy_opts
@click.option("--chaos", "chaos_spec", default=None,
              help="Fault injection, e.g. "
                   "'preempt=1,kill=1,stragglers=2,stall=1'. Keys: "
                   "preempt, preempt_at, kill, kill_at, stragglers, "
                   "straggler_factor, stall, stall_at.")
@click.option("--speculate", "sim_speculate", is_flag=True, default=False,
              help="Model cross-host straggler speculation (duplicate-issue "
                   "with first-ack-wins fencing) [default: "
                   "$IGNEOUS_SIM_SPECULATE].")
@click.option("--steal", "sim_steal", is_flag=True, default=False,
              help="Model idle-worker work stealing (unstarted tails carved "
                   "off long-held rounds) [default: $IGNEOUS_SIM_STEAL].")
@click.option("--what-if", "what_if_spec", default=None,
              help="Comma-separated alternative worker counts to forecast "
                   "alongside the base run, e.g. '1,8,32'.")
@click.option("--cost-per-worker-hour", default=0.0, show_default=True,
              type=float, help="Price forecasts in $ (0 = no cost column).")
@click.option("--emit-journal", "emit_path", default=None,
              help="Write the simulated run AS journal segments here — "
                   "`igneous fleet status|watch|top|trace` and the "
                   "Perfetto exporter work on it unchanged.")
@click.option("--base-ts", default=0.0, show_default=True, type=float,
              help="Timestamp anchor for --emit-journal (0 keeps output "
                   "bit-identical across same-seed reruns; pass a unix "
                   "time to overlay simulated history on live dashboards).")
@click.option("--json", "as_json", is_flag=True, help="Machine-readable.")
@click.option("--out", "out_path", default=None,
              help="Also write the full forecast JSON here (CI artifact).")
def fleet_simulate(queue_spec, journal_path, mine_path, model_path,
                   save_model_path, workers, tasks, seed, batch_size,
                   fail_scale, policy_mode, min_workers, max_workers,
                   as_horizon_sec, hysteresis, cooldown_sec, step_max,
                   chaos_spec, sim_speculate, sim_steal, what_if_spec,
                   cost_per_worker_hour, emit_path, base_ts, as_json,
                   out_path):
  """Forecast a campaign on virtual workers from mined journal history.

  Mines per-task-type empirical distributions (durations with their
  straggler tails, retry probabilities, lease-round overhead, worker
  speed spread) out of a real journal, then replays the campaign through
  a deterministic discrete-event simulation of the queue semantics —
  leases, redeliveries, DLQ, pre-lease rounds, preemption/kill/straggler
  chaos, and optionally the autoscale policy loop itself. Same seed,
  same model, same config => bit-identical forecast AND journal bytes."""
  import json as json_mod

  from . import secrets
  from .observability import replay, sim as sim_mod

  queue_spec = queue_spec or secrets.queue_url()
  if not model_path:
    mine_path = mine_path or _journal_location(queue_spec, journal_path)
  model = _load_or_mine_model(mine_path, model_path)
  if save_model_path:
    with open(save_model_path, "w") as f:
      json_mod.dump(model.to_dict(), f)

  chaos = sim_mod.ChaosSpec(**{
    k: (int(v) if k in ("preempt", "kill", "stragglers", "stall") else v)
    for k, v in _parse_kv_spec(chaos_spec).items()
  }) if chaos_spec else sim_mod.ChaosSpec()
  policy = _policy_from_opts(min_workers, max_workers, as_horizon_sec,
                             hysteresis, cooldown_sec, step_max)
  cfg = sim_mod.SimConfig.from_env(
    workers=workers, seed=seed, tasks=tasks, batch_size=batch_size,
    fail_scale=fail_scale, base_ts=base_ts,
    cost_per_worker_hour=cost_per_worker_hour,
    speculate=1 if sim_speculate else None,
    steal=1 if sim_steal else None,
  )
  cfg.chaos = chaos
  cfg.autoscale = policy_mode == "auto"
  cfg.policy = policy

  results = sim_mod.simulate(model, cfg, journal_path=emit_path)
  alternatives = []
  if what_if_spec:
    counts = [int(x) for x in what_if_spec.split(",") if x.strip()]
    alternatives = sim_mod.what_if(model, cfg, counts)

  payload = {
    "model": model.summary(),
    "config": {
      "workers": cfg.workers, "seed": cfg.seed,
      "batch_size": cfg.batch_size, "policy": policy_mode,
      "fail_scale": cfg.fail_scale, "tasks": results["tasks"],
    },
    "forecast": results,
    "what_if": alternatives,
  }
  if out_path:
    with open(out_path, "w") as f:
      json_mod.dump(payload, f, indent=2)
  if as_json:
    click.echo(json_mod.dumps(payload, indent=2))
    return

  ms = model.summary()
  click.echo(
    f"model: {ms['tasks_seen']} tasks mined across "
    f"{len(ms['task_types'])} type(s); round overhead p50 "
    f"{ms['round_overhead_p50_ms']}ms"
  )
  for name, t in ms["task_types"].items():
    click.echo(
      f"  {name:<30} n={t['count']:<6} p50 {t['p50_ms']}ms  "
      f"p95 {t['p95_ms']}ms  fail {t['fail_prob'] * 100:.1f}%"
    )
  r = results
  mode = "autoscaled" if cfg.autoscale else "fixed"
  click.echo(
    f"forecast ({mode}, {r['workers']} worker(s), seed {r['seed']}): "
    f"{r['tasks']} tasks in {r['makespan_sec']}s "
    f"({r['tasks_per_sec']}/s, utilization "
    f"{r['utilization'] * 100:.0f}%)"
  )
  click.echo(
    f"  completed {r['completed']}  dlq {r['dlq']}  retries "
    f"{r['failed_deliveries']}  lease recycles {r['lease_recycles']}  "
    f"released {r['released']}"
    + (f"  cost ${r['cost_usd']}" if r["cost_usd"] is not None else "")
  )
  spec, steals = r.get("speculation") or {}, r.get("steals") or {}
  if spec.get("issued") or steals.get("claims"):
    click.echo(
      f"  campaign survival: speculated {spec.get('issued', 0)} "
      f"(won {spec.get('won', 0)}, fenced {spec.get('fenced', 0)})  "
      f"steals {steals.get('claims', 0)} "
      f"({steals.get('tasks', 0)} task(s))"
    )
  if r["scale_events"]:
    click.echo(f"  scale events: {len(r['scale_events'])} "
               f"(peak {r['peak_workers']} workers)")
  if not r["completed_all"]:
    click.echo("  WARNING: campaign did not complete "
               f"(timed_out={r['timed_out']})")
  if alternatives:
    click.echo("what-if:")
    click.echo(f"  {'workers':>8}  {'makespan_s':>11}  {'delta':>8}  "
               f"{'dlq':>5}  {'util':>6}  cost")
    for alt in alternatives:
      delta = alt["makespan_sec"] - r["makespan_sec"]
      cost = f"${alt['cost_usd']}" if alt["cost_usd"] is not None else "-"
      click.echo(
        f"  {alt['workers']:>8}  {alt['makespan_sec']:>11}  "
        f"{delta:>+8.1f}  {alt['dlq']:>5}  "
        f"{alt['utilization'] * 100:>5.0f}%  {cost}"
      )
  if emit_path:
    click.echo(
      f"emitted {results['journal_segments']} journal segment(s) to "
      f"{emit_path} (try: igneous fleet status --journal {emit_path})"
    )


@fleet_group.command("autoscale")
@_journal_opts
@_autoscale_policy_opts
@click.option("--actuator", "actuator_kind",
              type=click.Choice(["local", "textfile", "command"]),
              default="local", show_default=True,
              help="local = spawn/drain real `igneous execute` "
                   "subprocesses; textfile = atomically publish the "
                   "target for an external reconciler; command = shell "
                   "out to a template with {n}.")
@click.option("--target-file", default=None,
              help="Path for --actuator textfile.")
@click.option("--scale-command", default=None,
              help="Template for --actuator command, e.g. "
                   "'kubectl scale --replicas={n} deploy/igneous-worker'.")
@click.option("--worker-arg", "worker_args", multiple=True,
              help="Extra args for spawned workers (local actuator), "
                   "repeatable.")
@click.option("--interval", default=None, type=float,
              help="Seconds between controller ticks "
                   "[default: $IGNEOUS_AUTOSCALE_INTERVAL_SEC or 15].")
@click.option("--iterations", default=None, type=int,
              help="Tick N times then exit [default: until drained or "
                   "Ctrl-C].")
@click.option("--drain-exit/--no-drain-exit", default=True,
              show_default=True,
              help="Exit once the backlog is empty and the pool is at "
                   "the policy floor (batch-campaign mode). "
                   "--no-drain-exit runs as a service.")
@click.option("--validate/--no-validate", default=True, show_default=True,
              help="Before touching the fleet, replay the mined journal "
                   "through the simulator under THIS policy and abort if "
                   "the simulated campaign fails to complete.")
@click.option("--json", "as_json", is_flag=True,
              help="One JSON object per controller decision.")
def fleet_autoscale(queue_spec, journal_path, min_workers, max_workers,
                    as_horizon_sec, hysteresis, cooldown_sec, step_max,
                    actuator_kind, target_file, scale_command, worker_args,
                    interval, iterations, drain_exit, validate, as_json):
  """Closed-loop fleet autoscaler: act on the HealthEngine's
  desired_workers signal.

  Each tick reads the journal + live queue depth, runs the SAME policy
  formula the health report and the simulator use, damps it (hysteresis,
  cooldown, step cap), and actuates. Scale-down is always graceful
  SIGTERM drain; nothing is ever killed."""
  import json as json_mod
  import time as time_mod

  from . import secrets
  from .observability import autoscale, sim as sim_mod
  from .queues import TaskQueue

  queue_spec = queue_spec or secrets.queue_url()
  if not queue_spec:
    raise click.UsageError("fleet autoscale needs a queue (-q or "
                           "$QUEUE_URL): backlog drives the policy")
  path = _journal_location(queue_spec, journal_path)
  policy = _policy_from_opts(min_workers, max_workers, as_horizon_sec,
                             hysteresis, cooldown_sec, step_max)

  if validate:
    from .observability import replay

    try:
      model = replay.mine_journal(path)
    except Exception:
      model = None
    if model and model.task_types:
      cfg = sim_mod.SimConfig.from_env(workers=policy.min_workers)
      cfg.autoscale = True
      cfg.policy = policy
      forecast = sim_mod.simulate(model, cfg)
      if not forecast["completed_all"]:
        raise click.ClickException(
          "policy validation failed: the simulated campaign did not "
          f"complete (dlq={forecast['dlq']}, "
          f"timed_out={forecast['timed_out']}). Loosen the policy or "
          "pass --no-validate."
        )
      click.echo(
        f"policy validated in simulation: {forecast['tasks']} tasks in "
        f"{forecast['makespan_sec']}s, peak {forecast['peak_workers']} "
        f"worker(s), {len(forecast['scale_events'])} scale event(s)",
        err=True,
      )
    else:
      click.echo("policy validation skipped: no task history to mine yet",
                 err=True)

  if actuator_kind == "local":
    actuator = autoscale.LocalPoolActuator(
      queue_spec, worker_args=list(worker_args),
    )
  elif actuator_kind == "textfile":
    if not target_file:
      raise click.UsageError("--actuator textfile needs --target-file")
    actuator = autoscale.TextfileActuator(target_file)
  else:
    if not scale_command:
      raise click.UsageError("--actuator command needs --scale-command")
    actuator = autoscale.CommandActuator(scale_command)

  controller = autoscale.AutoscaleController(
    path, TaskQueue(queue_spec), actuator,
    policy=policy, interval_sec=interval,
  )
  n = 0
  try:
    while True:
      decision = controller.step()
      if as_json:
        click.echo(json_mod.dumps(decision))
      else:
        click.echo(
          f"[{time_mod.strftime('%H:%M:%S')}] backlog "
          f"{decision['backlog']}  rate {decision['per_worker_rate']}/s"
          f"/worker  {decision['current']} -> {decision['target']} "
          f"({decision['reason']})"
        )
      n += 1
      actuator.reap()
      if (
        drain_exit and decision["backlog"] <= 0
        and actuator.current() <= policy.min_workers
        and n > 1
      ):
        break
      if iterations is not None and n >= iterations:
        break
      time_mod.sleep(controller.interval_sec)
  finally:
    actuator.shutdown()
  summary = {
    "ticks": n,
    "actions": sum(1 for d in controller.history if d["actuated"]),
  }
  if isinstance(actuator, autoscale.LocalPoolActuator):
    summary["spawned"] = actuator.stats["spawned"]
    summary["drained"] = actuator.stats["drained"]
    summary["exits"] = actuator.stats["exits"]
  click.echo(json_mod.dumps(summary))


# closed-loop campaign driver (ISSUE 17)


@main.group("campaign")
def campaign_group():
  """Closed-loop campaign survival: autoscale + speculation + stealing.

  One driver process per campaign: each tick sizes the fleet from the
  journal (the `fleet autoscale` loop), publishes straggler flags, and
  twins the unfinished tails of range leases held by flagged or
  journal-projected-slow workers (first ack wins, losers are zombie-
  fenced, completions never double-count)."""


@campaign_group.command("run")
@_journal_opts
@_autoscale_policy_opts
@click.option("--tick-sec", default=None, type=float,
              help="Seconds between driver ticks "
                   "[default: $IGNEOUS_CAMPAIGN_TICK_SEC or 5].")
@click.option("--max-wall-sec", default=None, type=float,
              help="Abort (gracefully) after this much wall clock "
                   "[default: $IGNEOUS_CAMPAIGN_MAX_WALL_SEC; 0 = never].")
@click.option("--iterations", default=None, type=int,
              help="Tick N times then exit [default: until drained].")
@click.option("--speculate/--no-speculate", "speculate", default=None,
              help="Twin the tails of flagged/slow holders' range leases "
                   "[default: $IGNEOUS_CAMPAIGN_SPECULATE or on].")
@click.option("--steal/--no-steal", "steal", default=None,
              help="Let idle workers claim unstarted sub-ranges off "
                   "long-held range leases [default: $IGNEOUS_STEAL "
                   "or off].")
@click.option("--worker-arg", "worker_args", multiple=True,
              help="Extra args for spawned workers, repeatable "
                   "(e.g. --worker-arg=--batch-size=4).")
@click.option("--actuator", "actuator_kind",
              type=click.Choice(["local", "textfile", "command"]),
              default="local", show_default=True,
              help="How scale actions reach the fleet (see fleet "
                   "autoscale).")
@click.option("--target-file", default=None,
              help="Path for --actuator textfile.")
@click.option("--scale-command", default=None,
              help="Template for --actuator command with {n}.")
@click.option("--json", "as_json", is_flag=True,
              help="One JSON object per tick + a summary object.")
def campaign_run(queue_spec, journal_path, min_workers, max_workers,
                 as_horizon_sec, hysteresis, cooldown_sec, step_max,
                 tick_sec, max_wall_sec, iterations, speculate, steal,
                 worker_args, actuator_kind, target_file, scale_command,
                 as_json):
  """Run a campaign to completion on a hostile fleet.

  Glues the survival layer into one loop: autoscale sizes the fleet,
  health flags route queue depth away from stragglers, speculation
  twins their unfinished tails, and (with --steal) idle workers carve
  unstarted sub-ranges off long-held leases. Exits when the queue is
  drained — no backlog, no outstanding leases, pool at the floor."""
  import json as json_mod
  import time as time_mod

  from . import secrets
  from .observability import autoscale, campaign as campaign_mod
  from .queues import TaskQueue

  queue_spec = queue_spec or secrets.queue_url()
  if not queue_spec:
    raise click.UsageError("campaign run needs a queue (-q or $QUEUE_URL)")
  path = _journal_location(queue_spec, journal_path)
  policy = _policy_from_opts(min_workers, max_workers, as_horizon_sec,
                             hysteresis, cooldown_sec, step_max)
  worker_env = {}
  if steal is not None:
    # ship the steal knob into every worker this driver spawns; the
    # driver process itself never steals (it holds no leases)
    knobs.set_env("IGNEOUS_STEAL", "1" if steal else "0")
    worker_env["IGNEOUS_STEAL"] = "1" if steal else "0"
  if actuator_kind == "local":
    actuator = autoscale.LocalPoolActuator(
      queue_spec, worker_args=list(worker_args), env=worker_env or None,
    )
  elif actuator_kind == "textfile":
    if not target_file:
      raise click.UsageError("--actuator textfile needs --target-file")
    actuator = autoscale.TextfileActuator(target_file)
  else:
    if not scale_command:
      raise click.UsageError("--actuator command needs --scale-command")
    actuator = autoscale.CommandActuator(scale_command)

  runner = campaign_mod.CampaignRunner(
    path, TaskQueue(queue_spec), actuator,
    policy=policy, tick_sec=tick_sec, speculate=speculate,
    max_wall_sec=max_wall_sec,
  )

  def narrate(sleep_sec):
    d = runner.history[-1]
    if as_json:
      click.echo(json_mod.dumps(d))
    else:
      extras = ""
      if d["speculated"]:
        extras += f"  speculated {d['speculated']}"
      if d["flagged"]:
        extras += f"  flagged {','.join(d['flagged'])}"
      click.echo(
        f"[{time_mod.strftime('%H:%M:%S')}] backlog {d['backlog']}  "
        f"workers {d['current']} -> {d['target']} ({d['reason']})"
        + extras
      )
    time_mod.sleep(sleep_sec)

  summary = runner.run(iterations=iterations, sleep_fn=narrate)
  click.echo(json_mod.dumps(summary if as_json else {
    k: v for k, v in summary.items() if k != "fleet_status"
  }))
  if summary["timed_out"] or summary["queue"].get("enqueued", 0) > 0:
    raise SystemExit(3)


# on-demand profiler capture (ISSUE 7)


@main.group("profile")
def profile_group():
  """On-demand ``jax.profiler`` capture across the fleet.

  ``capture`` publishes <journal>/profile/request.json; every worker
  polls it on the journal cadence (the PR 6 straggler-flag pattern) and
  runs one bounded profiler trace, uploading the TensorBoard-format
  artifacts under <journal>/profiles/. No worker restart, no always-on
  profiling cost."""


@profile_group.command("capture")
@_journal_opts
@click.option("--duration", default=5.0, show_default=True, type=float,
              help="Seconds of device activity to capture.")
@click.option("--worker", "workers", multiple=True,
              help="Restrict the trigger to these worker ids "
                   "[default: every worker captures once].")
@click.option("--wait", default=0.0, show_default=True, type=float,
              help="Poll up to this many seconds for artifacts to land "
                   "before returning (0 = fire and forget).")
@click.option("--local", is_flag=True,
              help="Capture in THIS process instead of publishing a "
                   "worker trigger (debugging a driver-side workload).")
def profile_capture(queue_spec, journal_path, duration, workers, wait,
                    local):
  """Trigger a bounded profiler capture on fleet workers."""
  import time as time_mod

  from . import secrets
  from .observability import device as device_mod
  from .observability import journal as journal_mod

  path = _journal_location(queue_spec or secrets.queue_url(), journal_path)
  if local:
    j = journal_mod.Journal(path, worker_id=f"profile-cli-{os.getpid()}")
    device_mod._capture_blocking(duration, j, "manual", None)
    for key in device_mod.list_profiles(path):
      click.echo(key)
    return
  req = device_mod.write_profile_request(
    path, duration_sec=duration, workers=list(workers) or None,
  )
  click.echo(f"published capture request {req['id']} "
             f"({duration}s) at {path}/{device_mod.PROFILE_REQUEST_KEY}")
  if wait <= 0:
    return
  deadline = time_mod.monotonic() + wait
  prefix = f"{device_mod.PROFILE_ARTIFACT_PREFIX}"
  while time_mod.monotonic() < deadline:
    found = [
      k for k in device_mod.list_profiles(path) if req["id"] in k
    ]
    if found:
      click.echo(f"{len(found)} artifact file(s):")
      for key in found:
        click.echo(f"  {prefix}{key}" if not key.startswith(prefix) else
                   f"  {key}")
      return
    time_mod.sleep(1.0)
  raise click.ClickException(
    f"no artifacts for request {req['id']} within {wait}s (are workers "
    "running with a journal?)"
  )


@profile_group.command("ls")
@_journal_opts
def profile_ls(queue_spec, journal_path):
  """List captured profile artifacts under <journal>/profiles/."""
  from . import secrets
  from .observability import device as device_mod

  path = _journal_location(queue_spec or secrets.queue_url(), journal_path)
  keys = device_mod.list_profiles(path)
  if not keys:
    click.echo("no profile artifacts")
    return
  for key in keys:
    click.echo(key)


@main.group()
def design():
  """Capacity planning math (reference cli.py `design`)."""


@design.command("ds-memory")
@click.argument("path")
@click.option("--memory", default=int(3.5e9), show_default=True)
@click.option("--mip", default=0, show_default=True)
@click.option("--factor", type=TUPLE3, default=(2, 2, 1), show_default=True)
@click.option("--max-mips", default=None, type=int,
              help="Cap the downsample count even if memory allows more.")
@click.option("--verbose", is_flag=True)
def design_ds_memory(path, memory, mip, factor, max_mips, verbose):
  """Optimal task shape + mip count for a byte budget
  (reference cli.py ds-memory)."""
  from .downsample_scales import (
    downsample_shape_from_memory_target,
    num_mips_from_memory_target,
  )
  from .volume import Volume

  vol = Volume(path, mip=mip)
  cs = vol.meta.chunk_size(mip)
  mips = num_mips_from_memory_target(
    memory, vol.dtype.itemsize, cs, factor, vol.num_channels
  )
  if max_mips is not None:
    mips = min(mips, max_mips)
  shape = downsample_shape_from_memory_target(
    vol.dtype.itemsize, int(cs.x), int(cs.y), int(cs.z), factor, memory,
    max_mips=max_mips, num_channels=vol.num_channels,
  )
  if verbose:
    click.echo(f"Data width: {vol.dtype.itemsize} B")
    click.echo(f"Chunk size: {int(cs.x)},{int(cs.y)},{int(cs.z)}")
    click.echo(f"Memory limit: {memory:.2e} B")
    click.echo(f"Optimized shape: {','.join(str(int(v)) for v in shape)}")
    click.echo(f"Downsamples: {mips}")
  else:
    click.echo(f"mips achievable in {memory:.2e} bytes: {mips}")
    click.echo(",".join(str(int(v)) for v in shape))


@design.command("ds-shape")
@click.argument("path")
@click.option("--memory", default=int(3.5e9), show_default=True)
@click.option("--mip", default=0, show_default=True)
@click.option("--factor", type=TUPLE3, default=(2, 2, 1), show_default=True)
@click.option("--num-mips", default=None, type=int)
def design_ds_shape(path, memory, mip, factor, num_mips):
  """Optimal task shape for a byte budget."""
  from .downsample_scales import downsample_shape_from_memory_target
  from .volume import Volume

  vol = Volume(path, mip=mip)
  cs = vol.meta.chunk_size(mip)
  shape = downsample_shape_from_memory_target(
    vol.dtype.itemsize, int(cs.x), int(cs.y), int(cs.z), factor, memory,
    max_mips=num_mips, num_channels=vol.num_channels,
  )
  click.echo(",".join(str(int(v)) for v in shape))


@design.command("bounds")
@click.argument("path")
@click.option("--mip", default=0, show_default=True)
def design_bounds(path, mip):
  """Reverse-engineer bounds from stored chunk filenames
  (reference cli.py:1628-1649 repair tool)."""
  from .lib import Bbox
  from .volume import Volume

  vol = Volume(path)
  boxes = []
  for key in vol.cf.list(f"{vol.meta.key(mip)}/"):
    try:
      boxes.append(Bbox.from_filename(key))
    except ValueError:
      continue
  if not boxes:
    click.echo("no chunks found")
    return
  total = Bbox.expand(*boxes)
  click.echo(f"chunks: {len(boxes)}")
  click.echo(f"bounds: {total}")
  click.echo(f"info bounds: {vol.meta.bounds(mip)}")


@main.command("view")
@click.argument("path")
@click.option("--port", default=1337, show_default=True)
@click.option("--browser/--no-browser", default=True, show_default=True,
              help="Open the link in the system browser.")
@click.option("--ng", default=None,
              help="Alternative Neuroglancer deployment URL.")
@click.option("--pos", type=TUPLE3, default=None,
              help="Open the view centered at this voxel position.")
@click.option("--name", default=None, help="Custom layer name.")
@click.option("--indirect", is_flag=True,
              help="Parity flag: the reference routes through CloudVolume "
                   "for private buckets; this build always serves the "
                   "local file server, which covers that case.")
def view_cmd(path, port, browser, ng, pos, name, indirect):
  """Serve PATH locally and print a Neuroglancer link
  (reference cli.py:1735-1850)."""
  import socket

  from .view import serve

  # skip to a free port like the reference (cli.py:1748-1754)
  for _ in range(10):
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as sock:
      if sock.connect_ex(("localhost", port)) != 0:
        break
    port += 1
  serve(
    path, port=port, block=True, browser=browser, ng_url=ng, position=pos,
    layer_name=name,
  )


@main.command("serve")
@click.argument("paths", nargs=-1, required=True)
@click.option("--port", default=8080, show_default=True)
@click.option("--host", default="0.0.0.0", show_default=True)
@click.option("--ram-mb", default=None, type=float,
              help="RAM cache budget (env IGNEOUS_SERVE_RAM_MB; default 256).")
@click.option("--ssd-dir", default=None,
              help="Local-SSD spill directory (env IGNEOUS_SERVE_SSD_DIR; "
                   "default off). Entries survive restarts.")
@click.option("--ssd-mb", default=None, type=float,
              help="SSD spill budget (env IGNEOUS_SERVE_SSD_MB; default 4096).")
@click.option("--synth/--no-synth", default=None,
              help="Synthesize missing mips on the fly from the parent "
                   "scale (env IGNEOUS_SERVE_SYNTH_MIPS; default on).")
@click.option("--writeback/--no-writeback", default=None,
              help="Write synthesized mips back to storage "
                   "(env IGNEOUS_SERVE_WRITEBACK; default off).")
@click.option("--cache-control", default=None,
              help="Cache-Control header for CDN fronting "
                   "(env IGNEOUS_SERVE_CACHE_CONTROL; "
                   "default 'public, max-age=300').")
@click.option("--journal", default=None,
              help="Journal cloudpath for request traces "
                   "(env IGNEOUS_JOURNAL).")
@click.option("--metrics-port", default=None, type=int,
              help="Prometheus /metrics port (also served inline at "
                   "/metrics on the main port; 0 auto-assigns).")
@click.option("--peers", default=None,
              help="Comma-separated replica base URLs: static federation "
                   "ring membership (env IGNEOUS_SERVE_FLEET_PEERS).")
@click.option("--peers-file", default=None,
              help="Shared membership directory cloudpath: replicas "
                   "heartbeat + discover the ring here "
                   "(env IGNEOUS_SERVE_FLEET_MEMBERSHIP).")
@click.option("--self-url", default=None,
              help="This replica's advertised base URL (env "
                   "IGNEOUS_SERVE_FLEET_SELF; default derived from the "
                   "bound host/port).")
@click.option("--prewarm/--no-prewarm", default=None,
              help="Telemetry-driven prefetch of predicted-hot chunks "
                   "mined from journal traces (env IGNEOUS_SERVE_PREWARM; "
                   "default off).")
def serve_cmd(paths, port, host, ram_mb, ssd_dir, ssd_mb, synth, writeback,
              cache_control, journal, metrics_port, peers, peers_file,
              self_url, prewarm):
  """Serve one or more Precomputed layers over HTTP (ISSUE 9).

  PATHS are cloudpaths, optionally named: ``name=gs://bucket/layer``.
  A single unnamed path also serves at the root (view parity); multiple
  layers serve under ``/<name>/``. The hot path hands stored bytes to
  clients without decoding (Content-Encoding negotiation), a multi-tier
  cache (RAM -> local SSD -> CDN via strong ETags) absorbs re-reads,
  concurrent misses for one chunk coalesce into a single backend fetch,
  and missing mips are synthesized through the device downsample
  kernels. SIGTERM drains gracefully and exits 0.
  """
  import json as json_mod
  import os as os_mod
  import signal as signal_mod
  import socket as socket_mod

  from .observability import journal as journal_mod
  from .observability import prom
  from .serve import Federation, ServeApp, ServeConfig, ServeServer

  layers = {}
  for spec in paths:
    if "=" in spec.split("://")[0]:
      name, _, cloudpath = spec.partition("=")
    else:
      cloudpath = spec
      name = cloudpath.rstrip("/").split("/")[-1] or "layer"
    if name in layers:
      raise click.UsageError(f"duplicate layer name: {name!r}")
    layers[name] = cloudpath
  default_layer = next(iter(layers)) if len(layers) == 1 else None

  jpath = journal if journal is not None else os_mod.environ.get(
    journal_mod.PATH_ENV
  )
  if jpath:
    worker_id = f"serve-{socket_mod.gethostname().split('.')[0]}-{os_mod.getpid()}"
    journal_mod.set_active(journal_mod.Journal(jpath, worker_id=worker_id))
  journal_mod.install_last_will({"role": "serve"})

  config = ServeConfig.from_env(
    ram_mb=ram_mb, ssd_dir=ssd_dir, ssd_mb=ssd_mb, synth_mips=synth,
    writeback=writeback, cache_control=cache_control,
  )
  federation = Federation.from_env(peers=peers, membership_dir=peers_file)
  app = ServeApp(layers, config=config, default_layer=default_layer,
                 federation=federation, prewarm=prewarm)
  server = ServeServer(app, host=host, port=port,
                       drain_timeout=config.drain_sec)
  bound_metrics = None
  if metrics_port is not None:
    bound_metrics = prom.start_http_server(metrics_port)
    if bound_metrics is not None:
      click.echo(f"metrics: http://0.0.0.0:{bound_metrics}/metrics")
  # the advertised URL needs the BOUND port (--port 0 auto-assigns),
  # so federation activates only after the listening socket exists
  if federation.configured:
    from .analysis import knobs as knobs_mod

    adv = self_url or knobs_mod.get_str("IGNEOUS_SERVE_FLEET_SELF")
    if not adv:
      adv_host = host
      if adv_host in ("0.0.0.0", "::", ""):
        adv_host = socket_mod.gethostname().split(".")[0]
      adv = f"http://{adv_host}:{server.server_address[1]}"
    federation.activate(adv)
  # machine-parsable readiness line (the CI smoke and orchestration
  # scripts wait on this rather than polling ports — it carries every
  # BOUND port so N auto-assigned replicas can boot on one host)
  click.echo(json_mod.dumps({
    "event": "serve.listening", "port": server.server_address[1],
    "host": host, "layers": sorted(layers),
    "metrics_port": bound_metrics,
    "self_url": federation.self_url if federation.configured else None,
  }), nl=True)

  def _on_signal(_signum, _frame):
    server.request_shutdown()

  signal_mod.signal(signal_mod.SIGTERM, _on_signal)
  signal_mod.signal(signal_mod.SIGINT, _on_signal)
  server.join()


@main.command("lint")
@click.option("--root", default=".", show_default=True,
              help="Repo root to analyze.")
@click.option("--knobs-md", is_flag=True,
              help="Print the generated README knob table.")
@click.option("--write", is_flag=True,
              help="With --knobs-md: rewrite README.md in place.")
@click.option("--baseline", default=None,
              help="Baseline file (repo-relative; default "
                   "tools/lint_baseline.json).")
@click.option("--update-baseline", is_flag=True,
              help="Accept current findings as the new baseline "
                   "(env-knobs/telemetry passes refuse).")
@click.option("--select", multiple=True,
              help="Run only these passes (repeatable).")
@click.option("--json", "as_json", is_flag=True,
              help="Machine-readable findings output.")
def lint_cmd(root, knobs_md, write, baseline, update_baseline, select,
             as_json):
  """Project-native static analysis (see README 'Static analysis')."""
  from igneous_tpu.analysis import runner

  for pid in select:
    if pid not in runner.PASS_IDS:
      raise click.BadParameter(
        f"unknown pass {pid!r}; choose from {', '.join(runner.PASS_IDS)}"
      )
  rc = runner.main(
    root, knobs_md=knobs_md, write=write, baseline_path=baseline,
    update_baseline=update_baseline, select=list(select) or None,
    as_json=as_json, echo=click.echo,
  )
  if rc:
    raise SystemExit(rc)


@main.command("tune")
@click.option("--out", default=None,
              help="Config root to write tuned/<device_kind>.json under "
                   "(default: IGNEOUS_TUNE_CONFIG or IGNEOUS_COMPILE_CACHE).")
@click.option("--budget", "budget_sec", type=float, default=None,
              help="Wall-clock budget for the whole sweep in seconds "
                   "(default: IGNEOUS_TUNE_BUDGET_SEC; unset = unbounded).")
@click.option("--repeats", type=int, default=None,
              help="Timed runs per candidate, best-of "
                   "(default: IGNEOUS_TUNE_REPEATS).")
@click.option("--size", type=int, default=48, show_default=True,
              help="Edge length of the seeded sweep workloads.")
@click.option("--knob", "only", multiple=True,
              help="Sweep only these knobs (repeatable; default: all).")
@click.option("--json", "as_json", is_flag=True,
              help="Print the full tuned config as JSON.")
def tune_cmd(out, budget_sec, repeats, size, only, as_json):
  """Autotune kernel knobs for this device kind (see README
  'Compile cache & autotuner').

  Sweeps Pallas CCL tile shapes, EDT line-block geometry, and page
  shape/batch on seeded workloads; every candidate must be
  byte-identical to the registry default. Winners are persisted as
  tuned/<device_kind>.json and picked up automatically (resolution:
  explicit env > tuned config > registry default).
  """
  import json

  from igneous_tpu import tune as tune_mod
  from igneous_tpu.analysis import knobs as knobs_mod
  for name in only:
    if name not in tune_mod.TUNABLE:
      raise click.BadParameter(
        f"unknown tunable {name!r}; choose from "
        f"{', '.join(tune_mod.TUNABLE)}"
      )
  pinned = [n for n in (only or tune_mod.TUNABLE) if knobs_mod.raw(n)]
  if pinned:
    raise click.ClickException(
      f"refusing to tune while {', '.join(pinned)} is pinned in the "
      "environment — explicit env always outranks tuned configs, so the "
      "sweep could never take effect; unset it first"
    )
  config = tune_mod.run(
    out=out, budget_sec=budget_sec, repeats=repeats, size=size,
    only=list(only) or None, log=click.echo,
  )
  if as_json:
    click.echo(json.dumps(config, indent=2, sort_keys=True))
    return
  if config["knobs"]:
    click.echo(f"tuned {len(config['knobs'])} knob(s): "
               + ", ".join(f"{k}={v}" for k, v in config["knobs"].items()))
  else:
    click.echo("registry defaults already optimal; nothing tuned")
  ratio = config.get("tune_best_vs_default_ratio")
  if ratio is not None:
    click.echo(f"tune_best_vs_default_ratio: {ratio}")
  if config.get("written_to"):
    click.echo(f"wrote {config['written_to']}")
  else:
    click.echo("no config root resolvable (pass --out or set "
               "IGNEOUS_TUNE_CONFIG / IGNEOUS_COMPILE_CACHE); "
               "config not persisted")


@main.command("license")
def license_cmd():
  click.echo("igneous-tpu is licensed under the BSD 3-Clause license.")


if __name__ == "__main__":
  main()
