"""The ``igneous-tpu`` command line interface.

Command-tree parity with the reference CLI
(/root/reference/igneous_cli/cli.py:185-214):
  image {downsample, xfer, create, rm, ccl {faces,links,calc-labels,
         relabel,clean,auto}}
  mesh {forge, merge, xfer, rm}
  skeleton {forge, merge, merge-sharded, xfer, rm}
  execute | queue {status,release,purge,rezero} | design {ds-memory,
  ds-shape, bounds} | license

Heavy imports (jax, task modules) happen inside commands so --help and
queue tooling stay instant.
"""

from __future__ import annotations

import os
import sys

import click


class Tuple3(click.ParamType):
  """'64,64,64' → (64, 64, 64) (reference cli.py:80-162 param types)."""

  name = "tuple3"

  def convert(self, value, param, ctx):
    if isinstance(value, (tuple, list)):
      return tuple(int(v) for v in value)
    try:
      parts = [int(v) for v in str(value).replace("x", ",").split(",")]
    except ValueError:
      self.fail(f"{value!r} is not an int triple like 64,64,64", param, ctx)
    if len(parts) != 3:
      self.fail(f"{value!r} must have exactly 3 components", param, ctx)
    return tuple(parts)


TUPLE3 = Tuple3()


def parse_id_list(value):
  """'5,6,7' → [5, 6, 7]; tolerant of blanks; None/empty → None."""
  if not value:
    return None
  try:
    ids = [int(tok) for tok in str(value).split(",") if tok.strip()]
  except ValueError:
    raise click.UsageError(f"not a comma-separated id list: {value!r}")
  return ids or None


def enqueue(queue_spec: str, tasks, parallel: int = 1):
  from .queues import LocalTaskQueue, TaskQueue

  if queue_spec is None:
    LocalTaskQueue(parallel=parallel).insert(tasks)
  else:
    TaskQueue(queue_spec).insert(tasks)


@click.group()
@click.option("-p", "--parallel", default=1, show_default=True,
              help="Worker processes for local execution.")
@click.pass_context
def main(ctx, parallel):
  """igneous-tpu: TPU-native Neuroglancer Precomputed pipelines."""
  ctx.ensure_object(dict)
  ctx.obj["parallel"] = parallel


# ---------------------------------------------------------------------------
# image


@main.group()
def image():
  """Downsample, transfer, ingest, delete image/segmentation layers."""


@image.command("downsample")
@click.argument("path")
@click.option("--queue", "-q", default=None, help="fq:// queue (local if omitted)")
@click.option("--mip", default=0, show_default=True)
@click.option("--num-mips", default=5, show_default=True)
@click.option("--factor", type=TUPLE3, default=None, help="e.g. 2,2,1")
@click.option("--isotropic", is_flag=True,
              help="Per-mip factors driving the resolution toward isotropy.")
@click.option("--sparse", is_flag=True)
@click.option("--sharded", is_flag=True)
@click.option("--fill-missing", is_flag=True)
@click.option("--chunk-size", type=TUPLE3, default=None)
@click.option("--encoding", default=None)
@click.option("--memory", "memory_target", default=int(3.5e9), show_default=True)
@click.option("--method", "downsample_method", default="auto", show_default=True)
@click.option("--batched", is_flag=True,
              help="Run on this host's device mesh now (K cutouts per "
                   "shard_map dispatch, double-buffered IO) instead of "
                   "enqueuing per-cutout tasks.")
@click.option("--batch-size", default=8, show_default=True,
              help="Cutouts per device dispatch with --batched.")
@click.option("--shape", type=TUPLE3, default=(256, 256, 64),
              show_default=True, help="Cutout shape with --batched.")
@click.pass_context
def image_downsample(ctx, path, queue, mip, num_mips, factor, isotropic,
                     sparse, sharded, fill_missing, chunk_size, encoding,
                     memory_target, downsample_method, batched, batch_size,
                     shape):
  """Build the downsample pyramid of PATH."""
  from . import task_creation as tc

  if isotropic:
    if factor is not None:
      raise click.UsageError("--isotropic and --factor are exclusive")
    factor = "isotropic"
  if batched:
    if sharded or queue:
      raise click.UsageError("--batched runs unsharded on this host (no -q)")
    if factor == "isotropic":
      raise click.UsageError("--batched uses one fixed --factor")
    if encoding or chunk_size:
      raise click.UsageError(
        "--batched downsamples in place; --encoding/--chunk-size apply "
        "only to the task factories"
      )
    from .parallel.batch_runner import batched_downsample

    stats = batched_downsample(
      path, mip=mip, num_mips=num_mips, shape=shape,
      batch_size=batch_size, factor=factor or (2, 2, 1), sparse=sparse,
      fill_missing=fill_missing, method=downsample_method,
    )
    click.echo(
      f"batched: {stats['batched_cutouts']} cutouts in "
      f"{stats['dispatches']} dispatches, {stats['edge_cutouts']} edge "
      f"cutouts via the task path"
    )
    return
  if sharded:
    tasks = tc.create_image_shard_downsample_tasks(
      path, mip=mip, fill_missing=fill_missing, sparse=sparse,
      chunk_size=chunk_size, encoding=encoding,
      factor=factor or (2, 2, 1), memory_target=memory_target,
      downsample_method=downsample_method,
    )
  else:
    tasks = tc.create_downsampling_tasks(
      path, mip=mip, num_mips=num_mips, fill_missing=fill_missing,
      sparse=sparse, chunk_size=chunk_size, encoding=encoding,
      factor=factor, memory_target=memory_target,
      downsample_method=downsample_method,
    )
  enqueue(queue, tasks, ctx.obj["parallel"])


@image.command("xfer")
@click.argument("src")
@click.argument("dest")
@click.option("--queue", "-q", default=None)
@click.option("--mip", default=0, show_default=True)
@click.option("--chunk-size", type=TUPLE3, default=None)
@click.option("--shape", type=TUPLE3, default=None)
@click.option("--translate", type=TUPLE3, default=(0, 0, 0))
@click.option("--fill-missing", is_flag=True)
@click.option("--sharded", is_flag=True)
@click.option("--encoding", default=None)
@click.option("--num-mips", default=0, show_default=True)
@click.pass_context
def image_xfer(ctx, src, dest, queue, mip, chunk_size, shape, translate,
               fill_missing, sharded, encoding, num_mips):
  """Transfer/rechunk/re-encode SRC into DEST."""
  from . import task_creation as tc

  if sharded:
    tasks = tc.create_image_shard_transfer_tasks(
      src, dest, mip=mip, chunk_size=chunk_size, encoding=encoding,
      translate=translate, fill_missing=fill_missing,
    )
  else:
    tasks = tc.create_transfer_tasks(
      src, dest, chunk_size=chunk_size, shape=shape, mip=mip,
      translate=translate, fill_missing=fill_missing, encoding=encoding,
      num_mips=num_mips,
    )
  enqueue(queue, tasks, ctx.obj["parallel"])


@image.command("create")
@click.argument("src")
@click.argument("dest")
@click.option("--resolution", type=TUPLE3, default=(1, 1, 1), show_default=True)
@click.option("--offset", type=TUPLE3, default=(0, 0, 0), show_default=True)
@click.option("--chunk-size", type=TUPLE3, default=(64, 64, 64), show_default=True)
@click.option("--layer-type", default=None,
              type=click.Choice(["image", "segmentation"]))
@click.option("--encoding", default="raw", show_default=True)
def image_create(src, dest, resolution, offset, chunk_size, layer_type, encoding):
  """Ingest an array file (npy/npy.gz/h5/nrrd/nii/nii.gz) as a Precomputed
  layer (reference `igneous image create`, cli.py:1852-1923; ckl needs
  the crackle library and fails with instructions)."""
  from .formats import load_volume_file
  from .volume import Volume

  try:
    arr = load_volume_file(src)
  except (ValueError, OSError) as e:  # OSError: corrupt gzip members
    raise click.UsageError(str(e))
  Volume.from_numpy(
    arr, dest, resolution=resolution, voxel_offset=offset,
    chunk_size=chunk_size, layer_type=layer_type, encoding=encoding,
  )
  click.echo(f"Created {dest} from {src} {arr.shape} {arr.dtype}")


@image.command("rm")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@click.option("--mip", default=0, show_default=True)
@click.option("--num-mips", default=0, show_default=True)
@click.pass_context
def image_rm(ctx, path, queue, mip, num_mips):
  """Delete image chunks at mip (… mip+num-mips)."""
  from . import task_creation as tc

  enqueue(queue, tc.create_deletion_tasks(path, mip=mip, num_mips=num_mips),
          ctx.obj["parallel"])


# -- image contrast ----------------------------------------------------------


@image.group("contrast")
def image_contrast():
  """Luminance histograms, contrast stretch, CLAHE."""


@image_contrast.command("histogram")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@click.option("--mip", default=0, show_default=True)
@click.option("--coverage", default=0.01, show_default=True)
@click.pass_context
def contrast_histogram(ctx, path, queue, mip, coverage):
  """Phase 1: per-z luminance histograms."""
  from . import task_creation as tc

  enqueue(queue, tc.create_luminance_levels_tasks(
    path, mip=mip, coverage_factor=coverage), ctx.obj["parallel"])


@image_contrast.command("equalize")
@click.argument("src")
@click.argument("dest")
@click.option("--queue", "-q", default=None)
@click.option("--mip", default=0, show_default=True)
@click.option("--clip-fraction", default=0.01, show_default=True)
@click.option("--shape", type=TUPLE3, default=None)
@click.pass_context
def contrast_equalize(ctx, src, dest, queue, mip, clip_fraction, shape):
  """Phase 2: histogram stretch using phase-1 levels."""
  from . import task_creation as tc

  enqueue(queue, tc.create_contrast_normalization_tasks(
    src, dest, mip=mip, clip_fraction=clip_fraction, shape=shape,
  ), ctx.obj["parallel"])


@image_contrast.command("clahe")
@click.argument("src")
@click.argument("dest")
@click.option("--queue", "-q", default=None)
@click.option("--mip", default=0, show_default=True)
@click.option("--clip-limit", default=40.0, show_default=True)
@click.option("--tile-grid", default=8, show_default=True)
@click.option("--shape", type=TUPLE3, default=(2048, 2048, 64), show_default=True)
@click.pass_context
def contrast_clahe(ctx, src, dest, queue, mip, clip_limit, tile_grid, shape):
  from . import task_creation as tc

  enqueue(queue, tc.create_clahe_tasks(
    src, dest, mip=mip, clip_limit=clip_limit, tile_grid_size=tile_grid,
    shape=shape,
  ), ctx.obj["parallel"])


# -- image voxels ------------------------------------------------------------


@image.group("voxels")
def image_voxels():
  """Voxel statistics."""


@image_voxels.command("count")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@click.option("--mip", default=0, show_default=True)
@click.option("--shape", type=TUPLE3, default=(512, 512, 512), show_default=True)
@click.pass_context
def voxels_count(ctx, path, queue, mip, shape):
  """Census phase; run `voxels sum` afterwards."""
  from . import task_creation as tc

  enqueue(queue, tc.create_voxel_counting_tasks(path, mip=mip, shape=shape),
          ctx.obj["parallel"])


@image_voxels.command("sum")
@click.argument("path")
@click.option("--mip", default=0, show_default=True)
def voxels_sum(path, mip):
  """Reduce census files into voxel_counts.im."""
  from . import task_creation as tc

  totals = tc.accumulate_voxel_counts(path, mip)
  click.echo(f"labels: {len(totals)}")


@image.command("roi")
@click.argument("path")
@click.option("--threshold", default=0.0, show_default=True)
@click.option("--dust", default=100, show_default=True)
def image_roi(path, threshold, dust):
  """Detect tissue regions of interest at the coarsest mip."""
  from . import task_creation as tc

  for roi in tc.compute_rois(path, threshold=threshold, dust_threshold=dust):
    click.echo(str(roi))


@image.command("reorder")
@click.argument("src")
@click.argument("dest")
@click.argument("mapping_json", type=click.Path(exists=True))
@click.option("--queue", "-q", default=None)
@click.option("--mip", default=0, show_default=True)
@click.pass_context
def image_reorder(ctx, src, dest, mapping_json, queue, mip):
  """Shuffle z-slices per a {dest_z: src_z} JSON mapping."""
  import json as json_mod

  from . import task_creation as tc

  with open(mapping_json) as f:
    mapping = json_mod.load(f)
  enqueue(queue, tc.create_reordering_tasks(src, dest, mapping, mip=mip),
          ctx.obj["parallel"])


# -- image ccl ---------------------------------------------------------------


@image.group("ccl")
def image_ccl():
  """Whole-image connected components labeling (4-pass)."""


_CCL_OPTS = [
  click.option("--mip", default=0, show_default=True),
  click.option("--shape", type=TUPLE3, default=(448, 448, 448), show_default=True),
  click.option("--threshold-gte", type=float, default=None),
  click.option("--threshold-lte", type=float, default=None),
  click.option("--fill-missing", is_flag=True),
]


def ccl_opts(fn):
  for opt in reversed(_CCL_OPTS):
    fn = opt(fn)
  return fn


@image_ccl.command("faces")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@ccl_opts
@click.pass_context
def ccl_faces(ctx, path, queue, mip, shape, threshold_gte, threshold_lte,
              fill_missing):
  from . import task_creation as tc

  enqueue(queue, tc.create_ccl_face_tasks(
    path, mip, shape, fill_missing, threshold_gte, threshold_lte,
  ), ctx.obj["parallel"])


@image_ccl.command("links")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@ccl_opts
@click.pass_context
def ccl_links(ctx, path, queue, mip, shape, threshold_gte, threshold_lte,
              fill_missing):
  from . import task_creation as tc

  enqueue(queue, tc.create_ccl_equivalence_tasks(
    path, mip, shape, fill_missing, threshold_gte, threshold_lte,
  ), ctx.obj["parallel"])


@image_ccl.command("calc-labels")
@click.argument("path")
@click.option("--mip", default=0, show_default=True)
def ccl_calc_labels(path, mip):
  """Single-machine global union-find (pass 3)."""
  from . import task_creation as tc

  max_label = tc.create_relabeling(path, mip)
  click.echo(f"max_label: {max_label}")


@image_ccl.command("relabel")
@click.argument("path")
@click.argument("dest")
@click.option("--queue", "-q", default=None)
@ccl_opts
@click.option("--encoding", default="compressed_segmentation", show_default=True)
@click.pass_context
def ccl_relabel(ctx, path, dest, queue, mip, shape, threshold_gte,
                threshold_lte, fill_missing, encoding):
  from . import task_creation as tc

  enqueue(queue, tc.create_ccl_relabel_tasks(
    path, dest, mip, shape, fill_missing, threshold_gte, threshold_lte,
    encoding=encoding,
  ), ctx.obj["parallel"])


@image_ccl.command("clean")
@click.argument("path")
@click.option("--mip", default=0, show_default=True)
def ccl_clean(path, mip):
  from . import task_creation as tc

  tc.clean_ccl_files(path, mip)


@image_ccl.command("auto")
@click.argument("path")
@click.argument("dest")
@ccl_opts
@click.option("--encoding", default="compressed_segmentation", show_default=True)
@click.pass_context
def ccl_auto_cmd(ctx, path, dest, mip, shape, threshold_gte, threshold_lte,
                 fill_missing, encoding):
  """All four passes locally (reference cli.py:799-852)."""
  from . import task_creation as tc
  from .queues import LocalTaskQueue

  max_label = tc.ccl_auto(
    path, dest, mip=mip, shape=shape,
    queue=LocalTaskQueue(parallel=ctx.obj["parallel"], progress=False),
    threshold_gte=threshold_gte, threshold_lte=threshold_lte,
    fill_missing=fill_missing, encoding=encoding,
  )
  click.echo(f"components: {max_label}")


# ---------------------------------------------------------------------------
# mesh


@main.group()
def mesh():
  """Mesh forging and management."""


@mesh.command("forge")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@click.option("--mip", default=0, show_default=True)
@click.option("--shape", type=TUPLE3, default=(448, 448, 448), show_default=True)
@click.option("--simplify-factor", default=100, show_default=True)
@click.option("--max-error", default=40, show_default=True)
@click.option("--mesh-dir", default=None)
@click.option("--dust-threshold", type=int, default=None)
@click.option("--fill-missing", is_flag=True)
@click.option("--sharded", is_flag=True)
@click.option("--spatial-index/--no-spatial-index", default=True, show_default=True)
@click.option("--obj-ids", default=None,
              help="comma-separated: mesh only these labels")
@click.option("--exclude-obj-ids", default=None,
              help="comma-separated: never mesh these labels")
@click.option("--mesher", default="cubes", show_default=True,
              type=click.Choice(["cubes", "tetrahedra"]))
@click.option("--simplify-parallel", default=1, show_default=True,
              help="threads for per-label simplification inside each task")
@click.pass_context
def mesh_forge(ctx, path, queue, mip, shape, simplify_factor, max_error,
               mesh_dir, dust_threshold, fill_missing, sharded, spatial_index,
               obj_ids, exclude_obj_ids, mesher, simplify_parallel):
  from . import task_creation as tc

  enqueue(queue, tc.create_meshing_tasks(
    path, mip=mip, shape=shape,
    simplification_factor=simplify_factor,
    max_simplification_error=max_error,
    mesh_dir=mesh_dir, dust_threshold=dust_threshold,
    fill_missing=fill_missing, sharded=sharded,
    spatial_index=spatial_index,
    object_ids=parse_id_list(obj_ids),
    exclude_object_ids=parse_id_list(exclude_obj_ids),
    mesher=mesher, parallel=simplify_parallel,
  ), ctx.obj["parallel"])


@mesh.command("merge")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@click.option("--magnitude", default=2, show_default=True)
@click.option("--mesh-dir", default=None)
@click.pass_context
def mesh_merge(ctx, path, queue, magnitude, mesh_dir):
  """Write legacy manifests (stage 2)."""
  from . import task_creation as tc

  enqueue(queue, tc.create_mesh_manifest_tasks(
    path, magnitude=magnitude, mesh_dir=mesh_dir), ctx.obj["parallel"])


@mesh.command("merge-sharded")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@click.option("--mesh-dir", default=None)
@click.option("--num-lods", default=2, show_default=True)
@click.pass_context
def mesh_merge_sharded(ctx, path, queue, mesh_dir, num_lods):
  """Sharded multires merge (requires a registered draco codec)."""
  from . import task_creation as tc

  enqueue(queue, tc.create_sharded_multires_mesh_tasks(
    path, mesh_dir=mesh_dir, num_lods=num_lods), ctx.obj["parallel"])


@mesh.group("spatial-index")
def mesh_spatial_index():
  """Mesh spatial-index maintenance."""


@mesh_spatial_index.command("create")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@click.option("--mip", default=0, show_default=True)
@click.option("--shape", type=TUPLE3, default=(448, 448, 448), show_default=True)
@click.option("--mesh-dir", default=None)
@click.pass_context
def mesh_spatial_index_create(ctx, path, queue, mip, shape, mesh_dir):
  from . import task_creation as tc
  from .tasks.mesh import mesh_dir_for
  from .volume import Volume

  mdir = mesh_dir_for(Volume(path), mesh_dir)
  enqueue(queue, tc.create_spatial_index_tasks(path, mdir, mip=mip,
                                               shape=shape),
          ctx.obj["parallel"])


@mesh_spatial_index.command("db")
@click.argument("path")
@click.argument("db_path", type=click.Path())
@click.option("--mesh-dir", default=None)
def mesh_spatial_index_db(path, db_path, mesh_dir):
  """Materialize the spatial index into a sqlite database."""
  from .spatial_index import SpatialIndex
  from .tasks.mesh import mesh_dir_for
  from .volume import Volume

  vol = Volume(path)
  mdir = mesh_dir_for(vol, mesh_dir)
  n = SpatialIndex(vol.cf, mdir).to_sqlite(db_path)
  click.echo(f"wrote {n} rows to {db_path}")


@mesh.command("clean")
@click.argument("path")
@click.option("--mesh-dir", default=None)
def mesh_clean(path, mesh_dir):
  """Delete stage-1 intermediates (fragment files, .frags containers,
  .spatial cells), keeping manifests and multires outputs."""
  from .tasks.mesh import mesh_dir_for
  from .volume import Volume

  vol = Volume(path)
  mdir = mesh_dir_for(vol, mesh_dir)
  doomed = [
    k for k in vol.cf.list(f"{mdir}/")
    if k.endswith(".frags") or k.endswith(".spatial")
    or len(k.split("/")[-1].split(":")) == 3  # label:0:bbox fragments
  ]
  vol.cf.delete(doomed)
  click.echo(f"deleted {len(doomed)} intermediate files")


@mesh.command("xfer")
@click.argument("src")
@click.argument("dest")
@click.option("--queue", "-q", default=None)
@click.option("--mesh-dir", default=None)
@click.option("--magnitude", default=1, show_default=True)
@click.pass_context
def mesh_xfer(ctx, src, dest, queue, mesh_dir, magnitude):
  from . import task_creation as tc

  enqueue(queue, tc.create_mesh_transfer_tasks(
    src, dest, mesh_dir=mesh_dir, magnitude=magnitude), ctx.obj["parallel"])


@mesh.command("rm")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@click.option("--mesh-dir", default=None)
@click.option("--magnitude", default=1, show_default=True)
@click.pass_context
def mesh_rm(ctx, path, queue, mesh_dir, magnitude):
  from . import task_creation as tc

  enqueue(queue, tc.create_mesh_deletion_tasks(
    path, magnitude=magnitude, mesh_dir=mesh_dir), ctx.obj["parallel"])


# ---------------------------------------------------------------------------
# skeleton


@main.group()
def skeleton():
  """Skeleton forging and management."""


@skeleton.command("forge")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@click.option("--mip", default=0, show_default=True)
@click.option("--shape", type=TUPLE3, default=(512, 512, 512), show_default=True)
@click.option("--scale", default=4.0, show_default=True, help="TEASAR scale")
@click.option("--const", default=500.0, show_default=True, help="TEASAR const (nm)")
@click.option("--dust-threshold", default=1000, show_default=True)
@click.option("--dust-global/--no-dust-global", default=False, show_default=True,
              help="dust by global voxel counts (requires a voxels census)")
@click.option("--fill-missing", is_flag=True)
@click.option("--sharded", is_flag=True)
@click.option("--skel-dir", default=None)
@click.option("--fix-borders/--no-fix-borders", default=True, show_default=True)
@click.option("--fix-branching/--no-fix-branching", default=True,
              show_default=True,
              help="regrow the path field from the whole tree before each "
                   "branch so junctions attach on-center")
@click.option("--fix-avocados", is_flag=True,
              help="absorb nucleus labels engulfed by a soma and "
                   "re-EDT the solid cell body")
@click.option("--soma-detect", default=1100.0, show_default=True,
              help="soma candidate EDT threshold (physical units)")
@click.option("--soma-accept", default=3500.0, show_default=True,
              help="soma acceptance EDT threshold (physical units)")
@click.option("--soma-scale", default=2.0, show_default=True)
@click.option("--soma-const", default=300.0, show_default=True)
@click.pass_context
def skeleton_forge(ctx, path, queue, mip, shape, scale, const, dust_threshold,
                   dust_global, fill_missing, sharded, skel_dir, fix_borders,
                   fix_branching, fix_avocados, soma_detect, soma_accept,
                   soma_scale, soma_const):
  from . import task_creation as tc

  enqueue(queue, tc.create_skeletonizing_tasks(
    path, mip=mip, shape=shape,
    teasar_params={
      "scale": scale, "const": const,
      "soma_detection_threshold": soma_detect,
      "soma_acceptance_threshold": soma_accept,
      "soma_invalidation_scale": soma_scale,
      "soma_invalidation_const": soma_const,
    },
    dust_threshold=dust_threshold, dust_global=dust_global,
    fill_missing=fill_missing,
    sharded=sharded, skel_dir=skel_dir, fix_borders=fix_borders,
    fix_branching=fix_branching, fix_avocados=fix_avocados,
  ), ctx.obj["parallel"])


@skeleton.command("merge")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@click.option("--magnitude", default=1, show_default=True)
@click.option("--skel-dir", default=None)
@click.option("--dust-threshold", default=4000.0, show_default=True)
@click.option("--tick-threshold", default=6000.0, show_default=True)
@click.option("--delete-fragments", is_flag=True)
@click.option("--max-cable-length", type=float, default=None,
              help="skip postprocessing (not upload) for merged skeletons "
                   "longer than this (nm) — bounds the cost of merge-error "
                   "monsters")
@click.pass_context
def skeleton_merge(ctx, path, queue, magnitude, skel_dir, dust_threshold,
                   tick_threshold, delete_fragments, max_cable_length):
  from . import task_creation as tc

  enqueue(queue, tc.create_unsharded_skeleton_merge_tasks(
    path, magnitude=magnitude, skel_dir=skel_dir,
    dust_threshold=dust_threshold, tick_threshold=tick_threshold,
    delete_fragments=delete_fragments, max_cable_length=max_cable_length,
  ), ctx.obj["parallel"])


@skeleton.command("merge-sharded")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@click.option("--skel-dir", default=None)
@click.option("--dust-threshold", default=4000.0, show_default=True)
@click.option("--tick-threshold", default=6000.0, show_default=True)
@click.option("--max-cable-length", type=float, default=None,
              help="skip postprocessing for merged skeletons longer than "
                   "this (nm)")
@click.pass_context
def skeleton_merge_sharded(ctx, path, queue, skel_dir, dust_threshold,
                           tick_threshold, max_cable_length):
  from . import task_creation as tc

  enqueue(queue, tc.create_sharded_skeleton_merge_tasks(
    path, skel_dir=skel_dir, dust_threshold=dust_threshold,
    tick_threshold=tick_threshold, max_cable_length=max_cable_length,
  ), ctx.obj["parallel"])


@skeleton.command("convert")
@click.argument("path")
@click.argument("out_dir", type=click.Path())
@click.option("--skel-dir", default=None)
@click.option("--labels", default=None, help="comma-separated label ids")
def skeleton_convert(path, out_dir, skel_dir, labels):
  """Export finished skeletons as SWC files
  (reference `igneous skeleton convert`)."""
  import os

  from .skeleton_io import Skeleton, to_swc
  from .tasks.skeleton import skel_dir_for
  from .volume import Volume

  vol = Volume(path)
  sdir = skel_dir_for(vol, skel_dir)
  attrs = (vol.cf.get_json(f"{sdir}/info") or {}).get("vertex_attributes")
  os.makedirs(out_dir, exist_ok=True)
  ids = parse_id_list(labels)
  wanted = set(ids) if ids else None
  n = 0
  for key in vol.cf.list(f"{sdir}/"):
    name = key.split("/")[-1]
    if not name.isdigit():
      continue
    label = int(name)
    if wanted is not None and label not in wanted:
      continue
    s = Skeleton.from_precomputed(vol.cf.get(key), vertex_attributes=attrs)
    with open(os.path.join(out_dir, f"{label}.swc"), "w") as f:
      f.write(to_swc(s, label=label))
    n += 1
  click.echo(f"wrote {n} swc files to {out_dir}")


@skeleton.group("spatial-index")
def skeleton_spatial_index():
  """Skeleton spatial-index maintenance."""


@skeleton_spatial_index.command("create")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@click.option("--mip", default=0, show_default=True)
@click.option("--shape", type=TUPLE3, default=(512, 512, 512), show_default=True)
@click.option("--skel-dir", default=None)
@click.pass_context
def skeleton_spatial_index_create(ctx, path, queue, mip, shape, skel_dir):
  """Rebuild the skeleton spatial index."""
  from . import task_creation as tc
  from .tasks.skeleton import skel_dir_for
  from .volume import Volume

  sdir = skel_dir_for(Volume(path), skel_dir)
  enqueue(queue, tc.create_spatial_index_tasks(path, sdir, mip=mip,
                                               shape=shape),
          ctx.obj["parallel"])


@skeleton_spatial_index.command("db")
@click.argument("path")
@click.argument("db_path", type=click.Path())
@click.option("--skel-dir", default=None)
def skeleton_spatial_index_db(path, db_path, skel_dir):
  """Materialize the skeleton spatial index into a sqlite database
  (reference `igneous skeleton spatial-index db`, cli.py:1565-1586)."""
  from .spatial_index import SpatialIndex
  from .tasks.skeleton import skel_dir_for
  from .volume import Volume

  vol = Volume(path)
  sdir = skel_dir_for(vol, skel_dir)
  n = SpatialIndex(vol.cf, sdir).to_sqlite(db_path)
  click.echo(f"wrote {n} rows to {db_path}")


@skeleton.command("clean")
@click.argument("path")
@click.option("--skel-dir", default=None)
def skeleton_clean(path, skel_dir):
  """Delete stage-1 intermediates (.sk fragments, .frags containers,
  .spatial cells), keeping the merged skeletons."""
  from .tasks.skeleton import skel_dir_for
  from .volume import Volume

  vol = Volume(path)
  sdir = skel_dir_for(vol, skel_dir)
  doomed = [
    k for k in vol.cf.list(f"{sdir}/")
    if k.endswith(".sk") or k.endswith(".frags") or k.endswith(".spatial")
  ]
  vol.cf.delete(doomed)
  click.echo(f"deleted {len(doomed)} intermediate files")


@skeleton.command("xfer")
@click.argument("src")
@click.argument("dest")
@click.option("--queue", "-q", default=None)
@click.option("--skel-dir", default=None)
@click.option("--magnitude", default=1, show_default=True)
@click.pass_context
def skeleton_xfer(ctx, src, dest, queue, skel_dir, magnitude):
  from . import task_creation as tc

  enqueue(queue, tc.create_skeleton_transfer_tasks(
    src, dest, skel_dir=skel_dir, magnitude=magnitude), ctx.obj["parallel"])


@skeleton.command("rm")
@click.argument("path")
@click.option("--queue", "-q", default=None)
@click.option("--skel-dir", default=None)
@click.option("--magnitude", default=1, show_default=True)
@click.pass_context
def skeleton_rm(ctx, path, queue, skel_dir, magnitude):
  from . import task_creation as tc

  enqueue(queue, tc.create_skeleton_deletion_tasks(
    path, magnitude=magnitude, skel_dir=skel_dir), ctx.obj["parallel"])


# ---------------------------------------------------------------------------
# execute / queue / design


@main.command("execute")
@click.argument("queue_spec", required=False)
@click.option("--lease-sec", default=None, type=int,
              help="Visibility timeout [default: $LEASE_SECONDS or 600].")
@click.option("-n", "num_tasks", default=None, type=int,
              help="Stop after N tasks.")
@click.option("--exit-on-empty", is_flag=True)
@click.option("--min-sec", default=-1.0, show_default=True,
              help="Keep polling at least this long (<0: forever).")
@click.option("--time", "timing", is_flag=True,
              help="Log per-task wall time + stage breakdown as JSON lines.")
@click.pass_context
def execute(ctx, queue_spec, lease_sec, num_tasks, exit_on_empty, min_sec,
            timing):
  """Worker poll loop: lease → run → delete
  (reference cli.py:888-964 semantics). QUEUE_SPEC falls back to the
  QUEUE_URL env var and --lease-sec to LEASE_SECONDS, so container CMDs
  stay declarative (secrets.py)."""
  from . import secrets

  queue_spec = queue_spec or secrets.queue_url()
  if not queue_spec:
    raise click.UsageError("provide QUEUE_SPEC or set $QUEUE_URL")
  if lease_sec is None:
    lease_sec = secrets.lease_seconds()
  parallel = ctx.obj["parallel"]
  if parallel > 1:
    import multiprocessing as mp

    # divide cores among workers for native kernel threading (same
    # oversubscription hygiene as the reference's cv2.setNumThreads(0))
    os.environ.setdefault(
      "IGNEOUS_POOL_THREADS", str(max(1, (os.cpu_count() or 1) // parallel))
    )
    ctx_mp = mp.get_context("spawn")
    procs = [
      ctx_mp.Process(
        target=_execute_worker,
        args=(queue_spec, lease_sec, num_tasks, exit_on_empty, min_sec,
              timing),
      )
      for _ in range(parallel)
    ]
    for p in procs:
      p.start()
    for p in procs:
      p.join()
    return
  _execute_worker(queue_spec, lease_sec, num_tasks, exit_on_empty, min_sec,
                  timing)


def _execute_worker(queue_spec, lease_sec, num_tasks, exit_on_empty, min_sec,
                    timing=False):
  import time

  import igneous_tpu.tasks  # noqa: F401  register all task classes
  from .queues import TaskQueue

  tq = TaskQueue(queue_spec)
  start = time.time()

  def stop_fn(executed: int, empty: bool) -> bool:
    if num_tasks is not None and executed >= num_tasks:
      return True
    if empty and exit_on_empty:
      return True
    if empty and 0 <= min_sec <= (time.time() - start):
      return True
    return False

  before_fn = after_fn = None
  if timing:
    from .telemetry import timed_poll_hooks

    before_fn, after_fn = timed_poll_hooks()

  executed = tq.poll(
    lease_seconds=lease_sec, verbose=True, stop_fn=stop_fn,
    before_fn=before_fn, after_fn=after_fn,
  )
  click.echo(f"executed {executed} tasks")


@main.group("queue")
def queue_group():
  """Queue inspection and maintenance (reference cli.py:1998-2054)."""


@queue_group.command("status")
@click.argument("queue_spec")
@click.option("--eta", is_flag=True, help="Sample throughput and estimate ETA.")
@click.option("--sample-sec", default=10.0, show_default=True)
def queue_status(queue_spec, eta, sample_sec):
  from .queues import TaskQueue

  tq = TaskQueue(queue_spec)
  click.echo(f"inserted: {tq.inserted}")
  click.echo(f"enqueued: {tq.enqueued}")
  click.echo(f"leased: {tq.leased}")
  click.echo(f"completed: {tq.completed}")
  if hasattr(tq, "lease_ages"):
    ages = tq.lease_ages()
    if ages:
      click.echo(f"lease_expiry_sec (min/max): {ages[0]:.0f}/{ages[-1]:.0f}")
  if eta:
    from .telemetry import queue_eta

    stats = queue_eta(tq, sample_seconds=sample_sec)
    click.echo(f"tasks/sec: {stats['tasks_per_sec']}")
    click.echo(f"eta_sec: {stats['eta_sec']}")


@queue_group.command("wait")
@click.argument("queue_spec")
@click.option("--interval", default=5.0, show_default=True,
              help="seconds between checks")
@click.option("--timeout", default=None, type=float,
              help="give up after this many seconds")
def queue_wait(queue_spec, interval, timeout):
  """Block until the queue is empty (reference `igneous queue wait`,
  cli.py:1974). Uses the backend's own emptiness semantics — for sqs://
  that includes the eventual-consistency double-confirmation."""
  import time as _time

  from .queues import TaskQueue

  q = TaskQueue(queue_spec)
  deadline = None if timeout is None else _time.monotonic() + timeout
  while True:
    if q.is_empty():
      click.echo("queue empty")
      return
    now = _time.monotonic()
    if deadline is not None and now >= deadline:
      raise click.ClickException(f"queue not empty after {timeout}s")
    # never sleep past the deadline (a long --interval must not make the
    # command overshoot --timeout)
    _time.sleep(interval if deadline is None else min(interval, deadline - now))


@queue_group.command("release")
@click.argument("queue_spec")
def queue_release(queue_spec):
  """Drop all leases (crashed workers' tasks return immediately)."""
  from .queues import TaskQueue

  TaskQueue(queue_spec).release_all()


@queue_group.command("purge")
@click.argument("queue_spec")
def queue_purge(queue_spec):
  from .queues import TaskQueue

  TaskQueue(queue_spec).purge()


@queue_group.command("rezero")
@click.argument("queue_spec")
def queue_rezero(queue_spec):
  from .queues import TaskQueue

  TaskQueue(queue_spec).rezero()


@queue_group.command("fsck")
@click.argument("queue_spec")
@click.option("--repair", is_flag=True,
              help="Quarantine malformed tasks, recycle bad leases.")
def queue_fsck(queue_spec, repair):
  """Audit queue consistency (malformed tasks, bad leases, counter drift)."""
  import json as json_mod

  from .queues import TaskQueue

  tq = TaskQueue(queue_spec)
  if not hasattr(tq, "fsck"):
    raise click.UsageError("fsck supports fq:// queues only")
  click.echo(json_mod.dumps(tq.fsck(repair=repair), indent=2))


@queue_group.command("cp")
@click.argument("src")
@click.argument("dest")
def queue_cp(src, dest):
  """Copy pending tasks between queues."""
  from .queues import copy_queue

  click.echo(f"copied {copy_queue(src, dest)} tasks")


@queue_group.command("mv")
@click.argument("src")
@click.argument("dest")
def queue_mv(src, dest):
  """Move pending tasks between queues."""
  from .queues import move_queue

  click.echo(f"moved {move_queue(src, dest)} tasks")


@main.group()
def design():
  """Capacity planning math (reference cli.py `design`)."""


@design.command("ds-memory")
@click.argument("path")
@click.option("--memory", default=int(3.5e9), show_default=True)
@click.option("--factor", type=TUPLE3, default=(2, 2, 1), show_default=True)
def design_ds_memory(path, memory, factor):
  """How many mips fit in a byte budget for PATH's chunk size."""
  from .downsample_scales import num_mips_from_memory_target
  from .volume import Volume

  vol = Volume(path)
  mips = num_mips_from_memory_target(
    memory, vol.dtype.itemsize, vol.chunk_size, factor, vol.num_channels
  )
  click.echo(f"mips achievable in {memory:.2e} bytes: {mips}")


@design.command("ds-shape")
@click.argument("path")
@click.option("--memory", default=int(3.5e9), show_default=True)
@click.option("--factor", type=TUPLE3, default=(2, 2, 1), show_default=True)
@click.option("--num-mips", default=None, type=int)
def design_ds_shape(path, memory, factor, num_mips):
  """Optimal task shape for a byte budget."""
  from .downsample_scales import downsample_shape_from_memory_target
  from .volume import Volume

  vol = Volume(path)
  cs = vol.chunk_size
  shape = downsample_shape_from_memory_target(
    vol.dtype.itemsize, int(cs.x), int(cs.y), int(cs.z), factor, memory,
    max_mips=num_mips, num_channels=vol.num_channels,
  )
  click.echo(",".join(str(int(v)) for v in shape))


@design.command("bounds")
@click.argument("path")
@click.option("--mip", default=0, show_default=True)
def design_bounds(path, mip):
  """Reverse-engineer bounds from stored chunk filenames
  (reference cli.py:1628-1649 repair tool)."""
  from .lib import Bbox
  from .volume import Volume

  vol = Volume(path)
  boxes = []
  for key in vol.cf.list(f"{vol.meta.key(mip)}/"):
    try:
      boxes.append(Bbox.from_filename(key))
    except ValueError:
      continue
  if not boxes:
    click.echo("no chunks found")
    return
  total = Bbox.expand(*boxes)
  click.echo(f"chunks: {len(boxes)}")
  click.echo(f"bounds: {total}")
  click.echo(f"info bounds: {vol.meta.bounds(mip)}")


@main.command("view")
@click.argument("path")
@click.option("--port", default=1337, show_default=True)
def view_cmd(path, port):
  """Serve PATH locally and print a Neuroglancer link
  (reference cli.py:1735-1850)."""
  from .view import serve

  serve(path, port=port, block=True)


@main.command("license")
def license_cmd():
  click.echo("igneous-tpu is licensed under the BSD 3-Clause license.")


if __name__ == "__main__":
  main()
