"""Benchmark vs BASELINE.md: downsample mip0→4 throughput, TPU vs CPU.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "vox/s", "vs_baseline": N, "detail": {...}}

Headline metric: sustained device-kernel throughput of the real pooling
pyramid (BASELINE.md configs 1+2: average uint8 and mode/COUNTLESS uint64,
mip 0→4) on chunk batches resident in HBM — kernel-vs-kernel against the
numpy oracle credited with perfect 8-core scaling. This mirrors how the
reference's tinybrain numbers are kernel-level (SURVEY.md §6).

detail.e2e_* reports the full pipeline (mem:// volumes, LocalTaskQueue,
codecs, host↔device transfers). NOTE: in this environment the TPU is
reached through a tunnel measured at ~10-15 MB/s host↔device (see
detail.transfer_MBps), which caps ANY e2e device pipeline below CPU numpy
regardless of kernel speed; on a directly-attached TPU (PCIe/ICI ~100+
GB/s) the e2e figure approaches the kernel figure.

Supervision (round-2 fix): the TPU relay sometimes stalls for hours, and a
stalled relay can hang ANY jax backend init in-process (the axon shim
patches jax's backend resolution at interpreter start). Round 1's bench
recorded 0 vox/s because of exactly that. This script therefore runs as a
supervisor by default: it probes the tunnel in a disposable subprocess,
runs the real bench as a supervised child with a deadline, and if the
tunnel is stalled falls back to an XLA-CPU child in a scrubbed
environment (shim disabled) so the driver always receives a real,
clearly-labeled number instead of a watchdog zero.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

from igneous_tpu.analysis import knobs

QUICK = bool(int(os.environ.get("BENCH_QUICK", "0")))
INIT_TIMEOUT_S = int(os.environ.get("BENCH_INIT_TIMEOUT", "240"))
CHILD_TIMEOUT_S = int(os.environ.get("BENCH_CHILD_TIMEOUT", "2400"))
_REPO_DIR = os.path.dirname(os.path.abspath(__file__))

IMG_SHAPE = (512, 512, 64) if QUICK else (1024, 1024, 128)
SEG_SHAPE = (256, 256, 64) if QUICK else (512, 512, 256)
NUM_MIPS = 4
KERNEL_ITERS = 3 if QUICK else 10


# ---------------------------------------------------------------------------
# supervisor


def _scrubbed_cpu_env() -> dict:
  from __graft_entry__ import _scrubbed_cpu_env as scrub

  return scrub()


def _probe_tpu(timeout_s: float) -> bool:
  """Can a fresh interpreter reach an actual accelerator without hanging?
  A fast axon-init failure falls back to the cpu platform with rc 0, so
  rc alone is not evidence of a live device — check the platform name."""
  try:
    proc = subprocess.run(
      [sys.executable, "-c",
       "import jax; d = jax.devices(); print(d[0].platform)"],
      capture_output=True, text=True, timeout=timeout_s, cwd=_REPO_DIR,
    )
    return proc.returncode == 0 and proc.stdout.strip() in ("axon", "tpu")
  except subprocess.TimeoutExpired:
    return False


def _run_child(mode: str, env: dict, timeout_s: float):
  """Run `bench.py --child <mode>`; return its JSON result dict or None."""
  try:
    proc = subprocess.run(
      [sys.executable, os.path.abspath(__file__), "--child", mode],
      env=env, capture_output=True, text=True, timeout=timeout_s,
      cwd=_REPO_DIR,
    )
  except subprocess.TimeoutExpired:
    return None
  if proc.returncode != 0:
    sys.stderr.write(proc.stderr)
    return None
  for line in reversed(proc.stdout.strip().splitlines()):
    try:
      return json.loads(line)
    except (json.JSONDecodeError, ValueError):
      continue
  return None


def supervise():
  deadline = time.time() + INIT_TIMEOUT_S
  tpu_ok = False
  while time.time() < deadline:
    if _probe_tpu(min(45, max(5, deadline - time.time()))):
      tpu_ok = True
      break
    time.sleep(5)

  result = None
  if tpu_ok:
    result = _run_child("tpu", dict(os.environ), CHILD_TIMEOUT_S)
  if result is None:
    # 8 virtual host devices so the CPU fallback still exercises the
    # device-pool batched path over a real mesh (VERDICT r5 item 6: the
    # official artifact must show batched-vs-solo on SOME device path)
    from __graft_entry__ import _scrubbed_cpu_env as scrub_n

    fb = _run_child("cpu", scrub_n(8), CHILD_TIMEOUT_S)
    if fb is not None:
      fb.setdefault("detail", {})["platform"] = (
        "cpu-fallback (TPU tunnel stalled)" if not tpu_ok
        else "cpu-fallback (TPU child failed)"
      )
      result = fb
  if result is None:
    result = {
      "metric": "downsample_kernel_mip0to4_voxels_per_sec",
      "value": 0, "unit": "vox/s", "vs_baseline": 0,
      "detail": {"error": "both TPU and CPU bench children failed"},
    }
  print(json.dumps(result))


# ---------------------------------------------------------------------------
# data


def make_data():
  rng = np.random.default_rng(0)
  img = rng.integers(0, 255, size=IMG_SHAPE).astype(np.uint8)
  blocks = rng.integers(1, 2**40, size=(16, 16, 16)).astype(np.uint64)
  reps = [s // 16 for s in SEG_SHAPE]
  seg = np.kron(blocks, np.ones(reps, dtype=np.uint64))
  seg[rng.random(SEG_SHAPE) < 0.02] = 0
  return img, seg


# ---------------------------------------------------------------------------
# kernel-level (device-resident)


def bench_device_kernels(img, seg):
  import jax
  import jax.numpy as jnp
  from functools import partial

  from igneous_tpu.ops.pooling import _pyramid_impl, _to_device_layout

  factors = tuple([(2, 2, 1)] * NUM_MIPS)

  # Timing on this runtime requires materializing a scalar that depends on
  # every output: block_until_ready on large device-resident outputs does
  # not reliably wait under the tunnel transport. The salt also defeats any
  # duplicate-dispatch caching.
  @partial(jax.jit, static_argnames=())
  def step(xi, lo, hi, salt):
    o_avg = _pyramid_impl(xi + salt.astype(xi.dtype), factors, "average", False)
    o_mode = _pyramid_impl(
      (lo ^ salt.astype(lo.dtype), hi), factors, "mode", False
    )
    chk = jnp.sum(o_avg[-1].astype(jnp.int32))
    for om in o_mode[-1]:
      chk = chk + jnp.sum(om.astype(jnp.int32))
    return chk

  xi = jax.device_put(_to_device_layout(img))
  lo = jax.device_put(_to_device_layout((seg & np.uint64(0xFFFFFFFF)).astype(np.uint32)))
  hi = jax.device_put(_to_device_layout((seg >> np.uint64(32)).astype(np.uint32)))

  float(step(xi, lo, hi, jnp.uint32(0)))  # compile + settle transfers

  t0 = time.perf_counter()
  for i in range(KERNEL_ITERS):
    float(step(xi, lo, hi, jnp.uint32(i + 1)))
  dt = (time.perf_counter() - t0) / KERNEL_ITERS
  return (img.size + seg.size) / dt


BEST_OF_N = 2 if QUICK else 3


RAW_SAMPLES: dict = {}  # callsite key -> every sample taken this run


def _best_of(once, n=BEST_OF_N, record=None):
  """Best-of-N throughput sampling. A single sample taken in a contended
  scheduler window can underreport by orders of magnitude (the round-3
  artifact recorded 46x below the real rate); the max over N samples is
  the least-contended estimate of what the kernels actually sustain.
  ``record`` keeps the raw samples (RAW_SAMPLES, emitted in the artifact)
  so cross-round comparisons can use min/median too — r01/r02 artifacts
  were single-sample and are comparable on median, not max."""
  samples = [once() for _ in range(n)]
  if record is not None:
    RAW_SAMPLES.setdefault(record, []).extend(samples)
  return max(samples)


def _sample_stats():
  return {
    key: {
      "n": len(s),
      "min": round(min(s), 1),
      "median": round(float(np.median(s)), 1),
      "max": round(max(s), 1),
    }
    for key, s in RAW_SAMPLES.items()
  }


def bench_cpu_kernels(img, seg):
  """Single-core CPU comparator rate (best-of-N). Prefers the native C++
  pooling kernels (oracle-verified semantics twins — the closest in-image
  stand-in for tinybrain, which a zero-egress build cannot vendor);
  falls back to the numpy oracles when no toolchain exists."""
  from igneous_tpu.native import pooling_lib
  from igneous_tpu.ops import oracle

  pooling_lib()  # build/load outside the timed region (g++ on first use)
  if (
    oracle.native_downsample_with_averaging(
      img[:64, :64, :16], (2, 2, 1), 1, parallel=1
    ) is not None
    and oracle.native_downsample_segmentation(
      seg[:64, :64, :16], (2, 2, 1), 1, parallel=1
    ) is not None
  ):
    def once():
      t0 = time.perf_counter()
      oracle.native_downsample_with_averaging(img, (2, 2, 1), NUM_MIPS, parallel=1)
      oracle.native_downsample_segmentation(seg, (2, 2, 1), NUM_MIPS, parallel=1)
      return (img.size + seg.size) / (time.perf_counter() - t0)
    return _best_of(once, BEST_OF_N, record="cpu_1core"), "native-C++ pooling x8-core credit"

  def once():
    t0 = time.perf_counter()
    oracle.np_downsample_with_averaging(img, (2, 2, 1), NUM_MIPS)
    oracle.np_downsample_segmentation(seg, (2, 2, 1), NUM_MIPS)
    return (img.size + seg.size) / (time.perf_counter() - t0)
  return _best_of(once, BEST_OF_N, record="cpu_1core"), "numpy-oracle kernels x8-core credit"


# ---------------------------------------------------------------------------
# end-to-end pipeline (includes storage codecs + transfers)


def _build_volumes(img, seg):
  from igneous_tpu.volume import Volume

  Volume.from_numpy(
    img, "mem://bench/img", chunk_size=(128, 128, 64), layer_type="image"
  )
  Volume.from_numpy(
    seg, "mem://bench/seg", chunk_size=(128, 128, 64), layer_type="segmentation"
  )


def _run_pipeline(path, sparse=False):
  from igneous_tpu import task_creation as tc
  from igneous_tpu.queues import LocalTaskQueue

  tasks = tc.create_downsampling_tasks(
    path, mip=0, num_mips=NUM_MIPS, sparse=sparse, compress=None,
    memory_target=int(1e9),
  )
  LocalTaskQueue(parallel=1, progress=False).insert(tasks)


def _timed_e2e(img, seg):
  from igneous_tpu.storage import clear_memory_storage

  clear_memory_storage()
  _build_volumes(img, seg)
  _run_pipeline("mem://bench/img")  # warmup compiles
  _run_pipeline("mem://bench/seg")
  clear_memory_storage()
  _build_volumes(img, seg)
  t0 = time.perf_counter()
  _run_pipeline("mem://bench/img")
  _run_pipeline("mem://bench/seg")
  dt = time.perf_counter() - t0
  return (img.size + seg.size) / dt


def bench_e2e(img, seg):
  """(serial_rate, pipeline_rate): the same task stream with the staged
  pipeline off (strict per-task serial — the pre-ISSUE-3 path, r05's
  e2e_pipeline_voxps comparable) and on (the ISSUE 3 subsystem)."""
  os.environ["IGNEOUS_PIPELINE"] = "off"
  try:
    serial = _timed_e2e(img, seg)
  finally:
    os.environ.pop("IGNEOUS_PIPELINE", None)
  pipelined = _timed_e2e(img, seg)
  return serial, pipelined


def bench_trace_overhead(img, seg):
  """(trace_overhead_pct, per-stage span summary) — ISSUE 5 acceptance:
  tracing at default sampling must cost <2% of e2e_pipeline wall time.
  Measures the SAME pipelined e2e stream with IGNEOUS_TRACE_SAMPLE=0
  (spans never allocate) vs =1 (every span records); the span batch from
  the traced run doubles as the per-stage summary BENCH reports."""
  from igneous_tpu.observability import trace as trace_mod

  prev = knobs.raw("IGNEOUS_TRACE_SAMPLE")

  def restore():
    if prev is None:
      os.environ.pop("IGNEOUS_TRACE_SAMPLE", None)
    else:
      os.environ["IGNEOUS_TRACE_SAMPLE"] = prev

  # interleaved pairs: on a shared 1-core host, run-to-run drift
  # (several %) exceeds the overhead being measured; back-to-back off/on
  # pairs + a median over 5 ratios keeps the recorded number honest
  off_rates, on_rates = [], []
  try:
    os.environ["IGNEOUS_TRACE_SAMPLE"] = "1"
    _timed_e2e(img, seg)  # discarded: pools/codecs/compiles all warm
    for _ in range(5):
      os.environ["IGNEOUS_TRACE_SAMPLE"] = "0"
      off_rates.append(_timed_e2e(img, seg))
      os.environ["IGNEOUS_TRACE_SAMPLE"] = "1"
      trace_mod.reset()  # only the LAST traced run's spans feed the summary
      on_rates.append(_timed_e2e(img, seg))
  finally:
    restore()
  # median of PAIRED ratios: each off/on pair ran back-to-back, so the
  # ratio cancels drift that max-of-runs would fold into the overhead
  ratios = sorted(
    off / on - 1.0 for off, on in zip(off_rates, on_rates) if on
  )
  overhead_pct = ratios[len(ratios) // 2] * 100.0 if ratios else None

  spans = trace_mod.drain_spans()
  by_name = {}
  for rec in spans:
    s = by_name.setdefault(rec["name"], {"count": 0, "total_s": 0.0})
    s["count"] += 1
    s["total_s"] += rec.get("dur", 0.0)
  summary = {
    name: {"count": s["count"], "total_s": round(s["total_s"], 4)}
    for name, s in sorted(by_name.items())
  }
  return (
    round(overhead_pct, 2) if overhead_pct is not None
    else _skip("no successful traced/untraced rate pairs"),
    summary,
  )


def bench_integrity_overhead(img, seg):
  """integrity_overhead_pct — ISSUE 16 acceptance: the checksummed
  write envelope (blake2b digest per put + batched manifest flushes)
  must cost <=5% of e2e_pipeline wall time on the clean path. Same
  interleaved-pair methodology as bench_trace_overhead: back-to-back
  off/on runs, median of paired ratios."""
  from igneous_tpu import integrity

  prev = knobs.raw("IGNEOUS_INTEGRITY")

  def restore():
    if prev is None:
      os.environ.pop("IGNEOUS_INTEGRITY", None)
    else:
      os.environ["IGNEOUS_INTEGRITY"] = prev

  off_rates, on_rates = [], []
  try:
    os.environ["IGNEOUS_INTEGRITY"] = "1"
    _timed_e2e(img, seg)  # discarded: pools/codecs/compiles all warm
    for _ in range(5):
      os.environ["IGNEOUS_INTEGRITY"] = "off"
      off_rates.append(_timed_e2e(img, seg))
      os.environ["IGNEOUS_INTEGRITY"] = "1"
      on_rates.append(_timed_e2e(img, seg))
  finally:
    restore()
    integrity.flush_all(swallow=True)
  ratios = sorted(
    off / on - 1.0 for off, on in zip(off_rates, on_rates) if on
  )
  if not ratios:
    return _skip("no successful envelope-on/off rate pairs")
  return round(ratios[len(ratios) // 2] * 100.0, 2)


def _run_batched(img, seg, mesh=None):
  from igneous_tpu.parallel.batch_runner import batched_downsample
  from igneous_tpu.storage import clear_memory_storage

  def run():
    batched_downsample(
      "mem://bench/img", mip=0, num_mips=NUM_MIPS,
      shape=(512, 512, 64), compress=None, mesh=mesh,
    )
    batched_downsample(
      "mem://bench/seg", mip=0, num_mips=NUM_MIPS,
      shape=(256, 256, 64), compress=None, mesh=mesh,
    )

  clear_memory_storage()
  _build_volumes(img, seg)
  run()  # warmup compiles
  clear_memory_storage()
  _build_volumes(img, seg)
  t0 = time.perf_counter()
  run()
  dt = time.perf_counter() - t0
  return (img.size + seg.size) / dt


def bench_e2e_batched(img, seg):
  """The production TPU path: K-cutout device dispatches with
  double-buffered download/upload (parallel/batch_runner.py) instead of
  one task at a time. Returns (host_path_rate, device_path_rate_or_None,
  path_label): the host rate keeps cross-round continuity; the device
  rate exercises the device-pool batched path whenever ANY mesh exists
  (virtual CPU devices included) so the batching win is driver-visible
  even while the TPU tunnel is down (VERDICT r5 item 6)."""
  host_rate = _run_batched(img, seg)

  import jax

  device_rate, label = None, "host-native (no mesh available)"
  if jax.device_count() > 1 or jax.default_backend() in ("axon", "tpu"):
    from igneous_tpu.parallel.executor import make_mesh

    os.environ["IGNEOUS_POOL_HOST"] = "0"  # pin the device pool path
    try:
      device_rate = _run_batched(img, seg, mesh=make_mesh())
    finally:
      os.environ.pop("IGNEOUS_POOL_HOST", None)
    label = (
      f"device-pool over {jax.device_count()} "
      f"{jax.default_backend()} device(s)"
    )
  return host_rate, device_rate, label


def measure_inflate_MBps(seg):
  """gunzip bandwidth of one stored chunk — the storage-codec wall that
  bounds any serial e2e rate on gzip-ingested layers (on an N-core host
  the pipeline can hide up to (N-1)/N of it behind compute)."""
  import gzip

  raw = np.ascontiguousarray(seg[:128, :128, :64]).tobytes()
  gz = gzip.compress(raw, compresslevel=6, mtime=0)
  rates = []
  for _ in range(3):
    t0 = time.perf_counter()
    gzip.decompress(gz)
    rates.append(len(raw) / (time.perf_counter() - t0) / 1e6)
  return round(max(rates), 1)


def bench_codecs(img, seg):
  """Per-codec bandwidth table (ISSUE 4 satellite): MB/s of DECODED bytes
  through each chunk codec + wire compressor, on one stored-chunk-sized
  cutout of the bench fixtures. ``cseg``/``compresso`` run their
  production path (native C++ where a toolchain exists, bulk-NumPy
  otherwise); ``zstd`` is None when the codec doesn't ship."""
  from igneous_tpu import codecs
  from igneous_tpu.storage import compress_bytes, decompress_bytes

  chunk = np.asfortranarray(seg[:128, :128, :64, np.newaxis])
  u8chunk = np.asfortranarray(img[:128, :128, :64, np.newaxis])
  out = {}

  def rate(nbytes, fn, n=3):
    best = min(_timed(fn) for _ in range(n))
    return round(nbytes / best / 1e6, 1)

  def _timed(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0

  raw_bytes = codecs.encode(chunk, "raw")
  out["raw_encode_MBps"] = rate(chunk.nbytes, lambda: codecs.encode(chunk, "raw"))
  out["raw_decode_MBps"] = rate(
    chunk.nbytes,
    lambda: codecs.decode(raw_bytes, "raw", chunk.shape, chunk.dtype, writable=False),
  )
  cs = codecs.encode(chunk, "compressed_segmentation")
  out["cseg_encode_MBps"] = rate(
    chunk.nbytes, lambda: codecs.encode(chunk, "compressed_segmentation")
  )
  out["cseg_decode_MBps"] = rate(
    chunk.nbytes,
    lambda: codecs.decode(cs, "compressed_segmentation", chunk.shape, chunk.dtype),
  )
  cp = codecs.encode(chunk, "compresso")
  out["compresso_encode_MBps"] = rate(
    chunk.nbytes, lambda: codecs.encode(chunk, "compresso")
  )
  out["compresso_decode_MBps"] = rate(
    chunk.nbytes, lambda: codecs.decode(cp, "compresso", chunk.shape, chunk.dtype)
  )
  # wire compressors measured over the raw u8 image chunk (the EM-image
  # common case; segmentation normally rides cseg/compresso underneath)
  u8raw = codecs.encode(u8chunk, "raw")
  gz = compress_bytes(u8raw, "gzip")
  out["gzip_deflate_MBps"] = rate(len(u8raw), lambda: compress_bytes(u8raw, "gzip"))
  out["gzip_inflate_MBps"] = rate(len(u8raw), lambda: decompress_bytes(gz, "gzip"))
  try:
    zs = compress_bytes(u8raw, "zstd")
    out["zstd_deflate_MBps"] = rate(len(u8raw), lambda: compress_bytes(u8raw, "zstd"))
    out["zstd_inflate_MBps"] = rate(len(u8raw), lambda: decompress_bytes(zs, "zstd"))
  except ImportError:
    out["zstd_deflate_MBps"] = _skip("zstandard not installed")
    out["zstd_inflate_MBps"] = _skip("zstandard not installed")
  return out


def bench_cseg_speedup():
  """Fast cseg paths vs the per-block loop reference (ISSUE 4 tentpole
  acceptance), on two fixtures: ``uniform`` — 16^3-celled segmentation
  (the realistic connectomics case: blocks interior to one object
  dominate, F-ordered like a download cutout); ``mixed`` — the same chunk
  with 2%% salt noise so nearly every block takes the sort path (worst
  case). ``fast`` is the production compress/decompress (native C++ here
  when a toolchain exists); ``numpy`` pins the pure bulk-NumPy fallback
  (IGNEOUS_TPU_NO_NATIVE honored per call)."""
  from igneous_tpu import cseg

  rng = np.random.default_rng(7)
  cells = rng.integers(1, 2**40, size=(8, 8, 4)).astype(np.uint64)
  uniform = np.asfortranarray(np.kron(cells, np.ones((16, 16, 16), np.uint64)))
  mixed = uniform.copy(order="F")
  mixed[rng.random(mixed.shape) < 0.02] = 0
  out = {}
  for name, labels in (("uniform", uniform), ("mixed", mixed)):
    shape4 = labels.shape + (1,)
    t0 = time.perf_counter()
    cseg._encode_channel_loop(labels, (8, 8, 8))
    enc_loop = time.perf_counter() - t0
    data = cseg.compress(labels)
    t0 = time.perf_counter()
    cseg._decompress_loop(data, shape4, np.uint64)
    dec_loop = time.perf_counter() - t0

    def best(fn, n=3):
      best_t = 1e9
      for _ in range(n):
        t0 = time.perf_counter()
        fn()
        best_t = min(best_t, time.perf_counter() - t0)
      return best_t

    enc_fast = best(lambda: cseg.compress(labels))
    dec_fast = best(lambda: cseg.decompress(data, shape4, np.uint64))
    os.environ["IGNEOUS_TPU_NO_NATIVE"] = "1"
    try:
      enc_np = best(lambda: cseg.compress(labels))
      dec_np = best(lambda: cseg.decompress(data, shape4, np.uint64))
    finally:
      os.environ.pop("IGNEOUS_TPU_NO_NATIVE", None)
    out[name] = {
      "encode_loop_ms": round(enc_loop * 1e3, 1),
      "decode_loop_ms": round(dec_loop * 1e3, 1),
      "fast_encode_speedup": round(enc_loop / enc_fast, 1),
      "fast_decode_speedup": round(dec_loop / dec_fast, 1),
      "numpy_encode_speedup": round(enc_loop / enc_np, 1),
      "numpy_decode_speedup": round(dec_loop / dec_np, 1),
    }
  return out


def bench_transfer_passthrough(seg):
  """Aligned same-geometry transfer throughput (ISSUE 4 tentpole): the
  compressed-domain passthrough (stored bytes move verbatim) vs the same
  transfer forced down the decode/re-encode path. Returns
  (passthrough_voxps, decode_voxps)."""
  from igneous_tpu import chunk_cache
  from igneous_tpu.storage import clear_memory_storage
  from igneous_tpu.tasks.image import TransferTask
  from igneous_tpu.volume import Volume

  sub = np.ascontiguousarray(seg[:256, :256, :128])
  clear_memory_storage()
  src = Volume.from_numpy(
    sub, "mem://bench/xfer_src", chunk_size=(128, 128, 64),
    layer_type="segmentation", encoding="compressed_segmentation",
  )

  def run_transfer(dest_path):
    chunk_cache.clear()
    task = TransferTask(
      src_path="mem://bench/xfer_src", dest_path=dest_path, mip=0,
      shape=sub.shape, offset=(0, 0, 0), skip_downsamples=True,
    )
    Volume.create(
      dest_path, Volume("mem://bench/xfer_src").info,
    )
    t0 = time.perf_counter()
    task.execute()
    return sub.size / (time.perf_counter() - t0)

  passthrough = max(run_transfer(f"mem://bench/xfer_pt{i}") for i in range(2))
  os.environ["IGNEOUS_TRANSFER_PASSTHROUGH"] = "off"
  try:
    decode = max(run_transfer(f"mem://bench/xfer_dec{i}") for i in range(2))
  finally:
    os.environ.pop("IGNEOUS_TRANSFER_PASSTHROUGH", None)
  clear_memory_storage()
  return round(passthrough, 1), round(decode, 1)


def bench_serve(seg):
  """Serving-tier latency/throughput over a seeded mem:// layer
  (ISSUE 9): hot-hit p50, overall p99, requests/sec over a keep-alive
  connection, and the coalescing dedupe ratio under a 16-client
  thundering herd on one cold chunk."""
  import http.client
  import threading

  from igneous_tpu.observability import metrics
  from igneous_tpu.serve import ServeApp, ServeConfig, ServeServer
  from igneous_tpu.volume import Volume

  sub = np.ascontiguousarray(seg[:128, :128, :64])
  vol = Volume.from_numpy(
    sub, "mem://bench/serve_layer", chunk_size=(64, 64, 32),
    layer_type="segmentation", encoding="compressed_segmentation",
  )
  del vol
  app = ServeApp(
    {"layer": "mem://bench/serve_layer"}, default_layer="layer",
    config=ServeConfig(ram_mb=64.0, synth_mips=False),
  )
  srv = ServeServer(app, host="127.0.0.1", port=0)
  port = srv.server_address[1]
  chunk_url = "/1_1_1/0-64_0-64_0-32"
  try:
    conn = http.client.HTTPConnection("127.0.0.1", port)
    lat = []
    n_requests = 300
    conn.request("GET", chunk_url)  # cold: populate the RAM tier
    conn.getresponse().read()
    t_all = time.perf_counter()
    for _ in range(n_requests):
      t0 = time.perf_counter()
      conn.request("GET", chunk_url, headers={"Accept-Encoding": "gzip"})
      conn.getresponse().read()
      lat.append(time.perf_counter() - t0)
    wall = time.perf_counter() - t_all
    conn.close()
    lat.sort()
    p50 = lat[len(lat) // 2]
    p99 = lat[min(int(len(lat) * 0.99), len(lat) - 1)]

    # thundering herd on one cold chunk: dedupe ratio = clients per
    # backend fetch the coalescer achieved
    app._cache.invalidate("layer")
    before = metrics.counters_snapshot()
    herd = 16
    barrier = threading.Barrier(herd)

    def hammer():
      c = http.client.HTTPConnection("127.0.0.1", port)
      barrier.wait()
      c.request("GET", chunk_url)
      c.getresponse().read()
      c.close()

    threads = [threading.Thread(target=hammer) for _ in range(herd)]
    for t in threads:
      t.start()
    for t in threads:
      t.join()
    after = metrics.counters_snapshot()
    leaders = after.get("serve.coalesce.leaders", 0) - before.get(
      "serve.coalesce.leaders", 0
    )
    waiters = after.get("serve.coalesce.waiters", 0) - before.get(
      "serve.coalesce.waiters", 0
    )
    dedupe = (leaders + waiters) / max(leaders, 1)
  finally:
    srv.shutdown()
  return {
    "serve_hot_hit_p50_ms": round(p50 * 1e3, 3),
    "serve_p99_ms": round(p99 * 1e3, 3),
    "serve_req_per_sec": round(n_requests / wall, 1),
    "serve_coalesce_dedupe_ratio": round(dedupe, 2),
  }


def bench_serve_fleet(seg):
  """Federated serving tier (ISSUE 18): three in-process replicas over
  one seeded mem:// layer joined by a static consistent-hash ring. A
  seeded zipfian herd (the stationary request mix of a million-user
  viewer population) runs round-robin across the replicas; reports
  fleet req/s, the peer-hit ratio (cold fills answered by the chunk's
  ring owner instead of origin), and the shed rate once one replica's
  admission gate is squeezed to a token-bucket far below offered load."""
  import http.client
  import threading

  from igneous_tpu.observability import metrics
  from igneous_tpu.serve import (
    Federation, QosGate, ServeApp, ServeConfig, ServeServer,
  )
  from igneous_tpu.volume import Volume

  sub = np.ascontiguousarray(seg[:128, :128, :64])
  Volume.from_numpy(
    sub, "mem://bench/serve_fleet_layer", chunk_size=(32, 32, 32),
    layer_type="segmentation",
  )
  replicas = []
  try:
    for _ in range(3):
      app = ServeApp(
        {"layer": "mem://bench/serve_fleet_layer"}, default_layer="layer",
        config=ServeConfig(ram_mb=64.0, synth_mips=False),
      )
      replicas.append(ServeServer(app, host="127.0.0.1", port=0))
    urls = [
      f"http://127.0.0.1:{srv.server_address[1]}" for srv in replicas
    ]
    for srv, url in zip(replicas, urls):
      fed = Federation(peers=urls, timeout_ms=5000.0, retry_sec=30.0)
      fed.activate(url)
      srv.app.federation = fed
    ports = [srv.server_address[1] for srv in replicas]

    rng = np.random.default_rng(7)
    keys = [
      f"1_1_1/{x}-{x+32}_{y}-{y+32}_{z}-{z+32}"
      for x in range(0, 128, 32)
      for y in range(0, 128, 32)
      for z in range(0, 64, 32)
    ]
    ranks = np.arange(1, len(keys) + 1, dtype=np.float64)
    pop = 1.0 / ranks ** 1.1
    pop /= pop.sum()
    n_requests = 150 if QUICK else 600
    clients = 8
    draws = rng.choice(len(keys), size=n_requests, p=pop)
    requests = [keys[d] for d in draws]
    per_client = [requests[i::clients] for i in range(clients)]
    barrier = threading.Barrier(clients)

    def viewer(ci):
      conns = {}
      barrier.wait()
      for j, key in enumerate(per_client[ci]):
        port = ports[(ci + j) % len(ports)]
        conn = conns.get(port)
        if conn is None:
          conn = conns[port] = http.client.HTTPConnection(
            "127.0.0.1", port
          )
        conn.request("GET", f"/{key}", headers={"Accept-Encoding": "gzip"})
        conn.getresponse().read()
      for conn in conns.values():
        conn.close()

    before = metrics.counters_snapshot()
    threads = [
      threading.Thread(target=viewer, args=(ci,)) for ci in range(clients)
    ]
    t_all = time.perf_counter()
    for t in threads:
      t.start()
    for t in threads:
      t.join()
    wall = time.perf_counter() - t_all
    after = metrics.counters_snapshot()
    peer_hits = after.get("serve.peer.hits", 0) - before.get(
      "serve.peer.hits", 0
    )
    fetches = after.get("serve.fetch", 0) - before.get("serve.fetch", 0)
    peer_hit_ratio = peer_hits / max(peer_hits + fetches, 1)

    # overload one replica: token bucket at ~2 rps vs a 200-request blast
    shed_app = replicas[0].app
    shed_app._qos = QosGate(rps=2.0, burst_sec=1.0, layer_names=["layer"])
    sheds = 0
    blast = 200
    conn = http.client.HTTPConnection("127.0.0.1", ports[0])
    for _ in range(blast):
      conn.request("GET", f"/{keys[0]}")
      resp = conn.getresponse()
      resp.read()
      if resp.status == 503:
        sheds += 1
    conn.close()
  finally:
    for srv in replicas:
      srv.shutdown()
  return {
    "serve_fleet_req_per_sec": round(n_requests / wall, 1),
    "serve_fleet_peer_hit_ratio": round(peer_hit_ratio, 3),
    "serve_fleet_shed_rate": round(sheds / blast, 3),
  }


def measure_transfer_MBps():
  import jax

  x = np.zeros(16 * 1024 * 1024, dtype=np.uint8)
  t0 = time.perf_counter()
  xd = jax.device_put(x)
  xd.block_until_ready()
  up = 16.0 / (time.perf_counter() - t0)
  t0 = time.perf_counter()
  np.asarray(xd)
  down = 16.0 / (time.perf_counter() - t0)
  return round(up, 1), round(down, 1)


def bench_mesh_kernel():
  """BASELINE config 3: marching-cubes count pass (the production
  mesher), BATCHED — K masks per shard_map dispatch (the per-voxel device
  stage; emission is O(surface) host work)."""
  from igneous_tpu.ops.mesh import _mc_count_kernel as _count_kernel
  from igneous_tpu.parallel.executor import BatchKernelExecutor

  n = 64 if QUICK else 128
  K = 4 if QUICK else 8
  g = np.indices((n, n, n)).astype(np.float32) - (n - 1) / 2
  mask = (np.sqrt((g**2).sum(0)) < n // 3).astype(np.uint8)
  batch = np.stack([mask.transpose(2, 1, 0)] * K)
  ex = BatchKernelExecutor(_count_kernel)

  ex(batch)  # compile
  t0 = time.perf_counter()
  iters = 2 if QUICK else 4
  for _ in range(iters):
    ex(batch)
  dt = (time.perf_counter() - t0) / iters
  return batch.size / dt


def bench_ccl_kernel(algo: str = "scan", force_device: bool = False):
  """BASELINE config 4: block CCL, BATCHED — K cutouts per shard_map
  dispatch (+ host renumber per chunk). ``algo`` selects the device
  kernel variant (scan = pointer jumps, relax = gather-free) so TPU runs
  record the ROADMAP hardware A/B.

  ``force_device`` (ISSUE 10 satellite): on the CPU fallback,
  connected_components_batch short-circuits to the native per-cutout
  union-find and silently IGNORES the algo knob — every "relax" number
  recorded through r05 was either null or the native path remeasured.
  Pinning IGNEOUS_CCL_BACKEND=device makes the relax kernel itself run
  (on the XLA CPU device). It is ~100x slower than native there, so the
  forced measurement uses the reduced block — vox/s normalizes size."""
  from igneous_tpu.ops.ccl import connected_components_batch

  os.environ["IGNEOUS_CCL_DEVICE_ALGO"] = algo
  if force_device:
    os.environ["IGNEOUS_CCL_BACKEND"] = "device"
  try:
    n = 64 if (QUICK or force_device) else 128
    K = 4 if (QUICK or force_device) else 8
    rng = np.random.default_rng(0)
    lab = (rng.integers(0, 3, (K, n, n, n)) * 7).astype(np.uint32)
    connected_components_batch(lab)  # compile
    t0 = time.perf_counter()
    connected_components_batch(lab)
    dt = time.perf_counter() - t0
    return lab.size / dt
  finally:
    os.environ.pop("IGNEOUS_CCL_DEVICE_ALGO", None)
    if force_device:
      os.environ.pop("IGNEOUS_CCL_BACKEND", None)


def bench_pool_ab():
  """Device-resident A/B of one 2x2x1 average-pool step: the Pallas
  hand-tiled kernel vs the XLA-fused formulation (same data, each in its
  preferred layout). TPU-only; the ROADMAP promotion decision needs this
  number."""
  import jax
  import jax.numpy as jnp

  from igneous_tpu.ops import pallas_pooling
  from igneous_tpu.ops.pooling import _pyramid_impl
  from functools import partial

  if not pallas_pooling.available():
    return None
  rng = np.random.default_rng(0)
  yxz = jax.device_put(
    jnp.asarray(rng.integers(0, 255, (1024, 1024, 128)).astype(np.uint8))
  )
  czyx = jax.device_put(jnp.transpose(yxz, (2, 0, 1))[None])

  pallas_fn = jax.jit(
    lambda x: jnp.sum(
      pallas_pooling._pool_zlast(x, "average", 8, 8, False).astype(jnp.int32)
    )
  )
  xla_fn = jax.jit(
    lambda x: jnp.sum(
      _pyramid_impl(x, ((2, 2, 1),), "average", False)[0].astype(jnp.int32)
    )
  )
  out = {}
  for name, fn, arg in (("pallas", pallas_fn, yxz), ("xla", xla_fn, czyx)):
    float(fn(arg))  # compile + settle
    t0 = time.perf_counter()
    iters = 2 if QUICK else 5
    for _ in range(iters):
      float(fn(arg))
    out[name + "_voxps"] = round(arg.size / ((time.perf_counter() - t0) / iters), 1)
  return out


def bench_infer():
  """ISSUE 10 headline: end-to-end InferenceTask campaign — halo'd
  download → batched jitted conv apply → overlap blend → Precomputed
  write — through the staged pipeline on mem:// storage, with a tiny
  fixed-seed conv net so the number tracks the machinery, not the model.
  Returns (voxels written per second, device busy ratio over the timed
  window, engine stats) — the busy ratio is the PR 7 ledger delta, i.e.
  the fraction of the campaign the device actually computed."""
  from igneous_tpu import task_creation as tc
  from igneous_tpu.infer import ModelSpec, init_params, save_model
  from igneous_tpu.observability.device import LEDGER
  from igneous_tpu.pipeline import run_tasks_pipelined
  from igneous_tpu.volume import Volume

  rng = np.random.default_rng(0)
  n = 128 if QUICK else 256
  nz = 32 if QUICK else 64
  data = rng.integers(0, 255, (n, n, nz, 1)).astype(np.uint8)
  src = "mem://bench/infer-src"
  model_path = "mem://bench/infer-model"
  Volume.from_numpy(data, src, chunk_size=(64, 64, 32), layer_type="image")
  spec = ModelSpec(
    "convnet3d", in_channels=1, out_channels=2,
    patch_shape=(64, 64, 32), overlap=(16, 16, 8), hidden=(8,),
  )
  save_model(model_path, spec, init_params(spec, seed=0))

  def campaign(dest):
    return list(tc.create_inference_tasks(
      src, dest, model_path, shape=(128, 128, 32), batch_size=4,
    ))

  # warm run: jit compile + model load land outside the timed window,
  # matching the steady state of a long campaign
  run_tasks_pipelined(campaign("mem://bench/infer-warm"))

  busy0 = LEDGER.busy_seconds()
  t0 = time.perf_counter()
  run_tasks_pipelined(campaign("mem://bench/infer-out"))
  wall = time.perf_counter() - t0
  busy = LEDGER.busy_seconds() - busy0
  voxels = int(np.prod(data.shape[:3]))
  return voxels / wall, (busy / wall if wall > 0 else None)


def bench_pool_ab_cpu(img):
  """CPU-device A/B of the 2x2x1 average-pool step (ISSUE 7 satellite):
  the batched XLA device path (ChunkExecutor over every virtual device)
  vs the native threaded host path, same voxels each side. Replaces the
  perpetual {"skipped": "tpu-only"} entry whenever >=2 (virtual) devices
  exist — the number behind the IGNEOUS_POOL_HOST=auto dispatch policy."""
  import jax

  from igneous_tpu.ops import oracle, pooling
  from igneous_tpu.parallel.executor import cached_chunk_executor, make_mesh

  n = jax.device_count()
  if n < 2:
    return None
  chunk = np.ascontiguousarray(img[:256, :256, :64])
  mesh = make_mesh()
  ex = cached_chunk_executor(mesh, factors=((2, 2, 1),), method="average")
  batch = np.stack([pooling._to_device_layout(chunk)] * n)
  iters = 2 if QUICK else 5

  ex(batch)  # compile + settle
  t0 = time.perf_counter()
  for _ in range(iters):
    ex(batch)
  device_rate = batch.size * iters / (time.perf_counter() - t0)

  host_fn = lambda: pooling.host_downsample(  # noqa: E731
    chunk, (2, 2, 1), 1, method="average", parallel=0
  )
  label = "native-threaded host pooling"
  if host_fn() is None:
    host_fn = lambda: oracle.np_downsample_with_averaging(  # noqa: E731
      chunk, (2, 2, 1), 1
    )
    label = "numpy-oracle host pooling"
  t0 = time.perf_counter()
  for _ in range(iters):
    for _k in range(n):  # same voxel count as the n-chunk device batch
      host_fn()
  host_rate = chunk.size * n * iters / (time.perf_counter() - t0)
  return {
    "device_voxps": round(device_rate, 1),
    "host_voxps": round(host_rate, 1),
    "device_vs_host": round(device_rate / host_rate, 3),
    "devices": n,
    "mode": f"cpu-device A/B: sharded XLA pyramid over {n} virtual "
            f"device(s) vs {label}",
  }


def bench_edt_kernel():
  """BASELINE config 5's device core: multilabel anisotropic EDT,
  BATCHED — K cutouts per shard_map dispatch."""
  from igneous_tpu.ops.edt import edt_batch

  n = 64 if QUICK else 128
  K = 4 if QUICK else 8
  rng = np.random.default_rng(0)
  lab = (rng.integers(0, 3, (K, n, n, n)) * 9).astype(np.uint32)
  edt_batch(lab, (4, 4, 40))  # compile
  t0 = time.perf_counter()
  edt_batch(lab, (4, 4, 40))
  dt = time.perf_counter() - t0
  return lab.size / dt


def bench_edt_device_kernel():
  """The device EDT kernel itself (blocked envelope scans, ISSUE 11
  tentpole 2), pinned to the device backend — without the pin, CPU
  fallback runs route edt_batch to the native/numpy host kernels and the
  device restructure would go unmeasured (the same silent-substitution
  trap ccl_relax fell into through r05). Reduced block: the XLA-CPU
  device is ~10x slower than native here; vox/s normalizes size."""
  from igneous_tpu.ops.edt import edt_batch

  os.environ["IGNEOUS_EDT_BACKEND"] = "device"
  try:
    n = 64 if QUICK else 64
    K = 4
    rng = np.random.default_rng(0)
    lab = (rng.integers(0, 3, (K, n, n, n)) * 9).astype(np.uint32)
    edt_batch(lab, (4, 4, 40))  # compile
    t0 = time.perf_counter()
    edt_batch(lab, (4, 4, 40))
    return lab.size / (time.perf_counter() - t0)
  finally:
    os.environ.pop("IGNEOUS_EDT_BACKEND", None)


def bench_mesh_extract_kernel():
  """Device mesh extraction (ISSUE 11 tentpole 3): count AND triangle
  emission on device (IGNEOUS_MESH_EMIT=device), solo marching_cubes on
  a half-dense random mask — the worst case for emission volume. The
  existing mesh_count_kernel_voxps times only the count pass."""
  from igneous_tpu.ops.mesh import marching_cubes

  os.environ["IGNEOUS_MESH_EMIT"] = "device"
  try:
    n = 64 if QUICK else 128
    rng = np.random.default_rng(0)
    mask = rng.random((n, n, n)) > 0.5
    marching_cubes(mask)  # compile both kernels
    iters = 2 if QUICK else 3
    t0 = time.perf_counter()
    for _ in range(iters):
      marching_cubes(mask)
    dt = (time.perf_counter() - t0) / iters
    return mask.size / dt
  finally:
    os.environ.pop("IGNEOUS_MESH_EMIT", None)


def bench_pyramid_fused(img):
  """The fused multi-mip walk (ISSUE 11 tentpole 4): mip0→3 in ONE
  compiled device program via pooling.downsample(mip_from=0), device
  kernels pinned (IGNEOUS_POOL_HOST=0) so CPU-fallback runs measure the
  fused XLA walk rather than the native per-mip host loop."""
  from igneous_tpu.ops import pooling

  chunk = np.ascontiguousarray(img[:256, :256, :64])
  os.environ["IGNEOUS_POOL_HOST"] = "0"
  try:
    pooling.downsample(chunk, (2, 2, 1), 3, method="average", mip_from=0)
    iters = 2 if QUICK else 5
    t0 = time.perf_counter()
    for _ in range(iters):
      pooling.downsample(chunk, (2, 2, 1), 3, method="average", mip_from=0)
    dt = (time.perf_counter() - t0) / iters
    return chunk.size / dt
  finally:
    os.environ.pop("IGNEOUS_POOL_HOST", None)


def bench_ragged():
  """Ragged paged batching (ISSUE 12): one mixed-shape boundary-cell
  fleet through the paged pyramid (ONE compiled signature + page slack)
  vs the same fleet through solo per-cutout downsample (a compile per
  distinct shape). Both run cold on purpose — the per-shape recompile
  tax is exactly what paging removes, so warmed-cache rates would
  measure the wrong thing. Device path pinned (IGNEOUS_POOL_HOST=0) so
  CPU-fallback rounds measure the paged kernel rather than the native
  host loop. Returns (batched_voxps, solo_voxps, pad_waste_pct)."""
  from igneous_tpu.observability import device as device_mod
  from igneous_tpu.ops import pooling
  from igneous_tpu.parallel.paged import paged_pyramid

  os.environ["IGNEOUS_POOL_HOST"] = "0"
  try:
    rng = np.random.default_rng(0)
    shapes = [(129, 256, 64), (256, 129, 64), (129, 129, 64),
              (65, 97, 33), (193, 65, 64)]
    if QUICK:
      shapes = shapes[:3]
    imgs = [rng.integers(0, 255, s).astype(np.uint8) for s in shapes]
    total = sum(i.size for i in imgs)

    led = device_mod.LEDGER
    pad0, real0 = led.pad_bytes, led.real_bytes
    t0 = time.perf_counter()
    paged_pyramid(imgs, (2, 2, 1), 2, method="average")
    batched = total / (time.perf_counter() - t0)
    pad = led.pad_bytes - pad0
    real = led.real_bytes - real0
    pad_waste_pct = (
      round(100.0 * pad / (pad + real), 2) if (pad + real) else None
    )

    t0 = time.perf_counter()
    for img in imgs:
      pooling.downsample(img, (2, 2, 1), 2, method="average")
    solo = total / (time.perf_counter() - t0)
    return batched, solo, pad_waste_pct
  finally:
    os.environ.pop("IGNEOUS_POOL_HOST", None)


_CACHE_BENCH_CHILD = r"""
import json, os, sys
import numpy as np
sys.path.insert(0, sys.argv[1])
from igneous_tpu.parallel import paged
from igneous_tpu.observability import device as dev
rng = np.random.default_rng(19)
imgs = [rng.integers(0, 255, s).astype(np.uint8)
        for s in [(48, 41, 25), (24, 24, 24), (43, 16, 9)]]
paged.paged_pyramid(imgs, (2, 2, 1), num_mips=2)
led = dev.LEDGER
print(json.dumps({
  "compile_s": sum(k["compile_s"] for k in led.kernels.values()),
  "cc": dict(led.compile_cache),
}))
"""


def bench_compile_cache():
  """Persistent compile cache (ISSUE 19): the same paged workload in two
  FRESH interpreters sharing one file:// cache. The cold child pays the
  XLA compiles and publishes executables; the warm child fetches. cold_s
  is the cold child's measured compile seconds, warm_s what the warm
  child paid instead (fetch + any residual compiles) — their ratio is
  the per-worker startup tax the cache removes fleet-wide. Returns
  (cold_s, warm_s) or None when the children fail (e.g. no
  serialize_executable support on this backend)."""
  import tempfile

  tmp = tempfile.mkdtemp(prefix="igneous-bench-cc-")

  def child():
    env = dict(os.environ)
    env.update({
      "JAX_PLATFORMS": "cpu",
      "PALLAS_AXON_POOL_IPS": "",
      "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
      "IGNEOUS_COMPILE_CACHE": f"file://{tmp}/cache",
    })
    env.pop("AXON_POOL_SVC_OVERRIDE", None)
    env.pop("AXON_LOOPBACK_RELAY", None)
    proc = subprocess.run(
      [sys.executable, "-c", _CACHE_BENCH_CHILD, _REPO_DIR],
      env=env, cwd=_REPO_DIR, capture_output=True, text=True,
      timeout=600,
    )
    if proc.returncode != 0:
      return None
    return json.loads(proc.stdout.strip().splitlines()[-1])

  cold = child()
  warm = child()
  if not cold or not warm:
    return None
  if not cold["cc"].get("puts") or not warm["cc"].get("hits"):
    return None  # the cache never engaged; a ratio would be fiction
  cold_s = cold["compile_s"]
  warm_s = warm["compile_s"] + warm["cc"].get("fetch_s", 0.0)
  return cold_s, warm_s


def bench_tune():
  """Autotuner (ISSUE 19): a budget-bounded `igneous tune` sweep on the
  live backend — best-vs-default ratio across the tunable knobs (1.0 =
  registry defaults already optimal; every candidate byte-identity
  checked inside the sweep). None when the sweep fails."""
  import tempfile

  from igneous_tpu import tune as tune_mod

  try:
    config = tune_mod.run(
      out=f"file://{tempfile.mkdtemp(prefix='igneous-bench-tune-')}",
      budget_sec=60.0 if QUICK else 180.0, repeats=2, size=32,
    )
  except Exception:
    return None
  return config.get("tune_best_vs_default_ratio")


def bench_host_kernels(img, seg):
  """The production path on an accelerator-less host: the native C++
  pooling kernels threaded across every core — exactly what
  ops.pooling.downsample_auto dispatches to when no TPU is attached (the
  same deal as the reference's tinybrain-on-CPU workers). None when the
  native lib is unavailable."""
  from igneous_tpu.ops import pooling

  warm = pooling.host_downsample(
    img, (2, 2, 1), NUM_MIPS, method="average", parallel=0
  )
  if warm is None:
    return None

  def once():
    t0 = time.perf_counter()
    pooling.host_downsample(img, (2, 2, 1), NUM_MIPS, method="average", parallel=0)
    pooling.host_downsample(seg, (2, 2, 1), NUM_MIPS, method="mode", parallel=0)
    return (img.size + seg.size) / (time.perf_counter() - t0)

  return _best_of(once, BEST_OF_N, record="host_kernel")


def bench_forge_pipelines():
  """e2e forge rates on a small blobby segmentation (BASELINE configs
  3/5 pipeline-level): mesh forge (sharded, device count pass + host
  emit/weld/QEM) and skeleton forge with exact cross-sections."""
  from igneous_tpu.volume import Volume
  from igneous_tpu import task_creation as tc
  from igneous_tpu.queues import LocalTaskQueue
  from igneous_tpu.storage import clear_memory_storage

  rng = np.random.default_rng(0)
  n = 64 if QUICK else 128
  g = np.indices((n, n, n)).astype(np.float32)
  seg = np.zeros((n, n, n), dtype=np.uint64)
  for i in range(8):
    c = rng.integers(n // 8, n - n // 8, 3)
    r = rng.integers(n // 12, n // 5)
    seg[((g[0] - c[0]) ** 2 + (g[1] - c[1]) ** 2 + (g[2] - c[2]) ** 2) < r * r] = i + 1
  clear_memory_storage()
  Volume.from_numpy(
    seg, "mem://bench/forge", resolution=(16, 16, 40),
    chunk_size=(n, n, n), layer_type="segmentation",
  )
  tq = LocalTaskQueue(parallel=1, progress=False)

  t0 = time.perf_counter()
  tq.insert(tc.create_meshing_tasks(
    "mem://bench/forge", shape=(n, n, n), sharded=True, spatial_index=True,
  ))
  mesh_dt = time.perf_counter() - t0

  t0 = time.perf_counter()
  tq.insert(tc.create_skeletonizing_tasks(
    "mem://bench/forge", shape=(n, n, n), dust_threshold=50,
    teasar_params={"scale": 4, "const": 200},
    cross_sectional_area=True, csa_smoothing_window=2,
  ))
  skel_dt = time.perf_counter() - t0
  clear_memory_storage()
  return round(seg.size / mesh_dt, 1), round(seg.size / skel_dt, 1)


def bench_queue():
  """Queue scale-out (ISSUE 15): the control-plane rates a 10M-task
  campaign lives or dies on, measured on a 100k-task fq:// queue —
  batched segment enqueue vs the classic one-file-per-task layout,
  range-lease acquisition throughput, and the `queue status` depth read
  (O(shards): task counts ride in segment file names)."""
  import shutil
  import tempfile

  from igneous_tpu.queues import FileQueue, PrintTask, serialize

  n = 20_000 if QUICK else 100_000
  n_classic = 1_000 if QUICK else 2_000
  payload = serialize(PrintTask("bench"))
  root = tempfile.mkdtemp(prefix="bench_queue_")
  try:
    cq = FileQueue(f"fq://{root}/classic")
    t0 = time.perf_counter()
    cq.insert(payload for _ in range(n_classic))
    classic_rate = n_classic / (time.perf_counter() - t0)

    q = FileQueue(f"fq://{root}/batched")
    t0 = time.perf_counter()
    q.insert_batch((payload for _ in range(n)), total=n)
    enqueue_rate = n / (time.perf_counter() - t0)

    t0 = time.perf_counter()
    for _ in range(3):
      snap = q.depth_snapshot()
    status_ms = (time.perf_counter() - t0) / 3 * 1e3
    assert snap["enqueued"] == n, snap

    target = min(n, 4_096 if QUICK else 20_480)
    leased = 0
    t0 = time.perf_counter()
    while leased < target:
      got = q.lease_batch(600, max_tasks=1024)
      if not got:
        break
      leased += len(got)
    lease_rate = leased / (time.perf_counter() - t0)
  finally:
    shutil.rmtree(root, ignore_errors=True)
  return (
    round(enqueue_rate, 1), round(lease_rate, 1), round(status_ms, 3),
    round(classic_rate, 1),
  )


def bench_campaign_survival():
  """Campaign survival (ISSUE 17): end-to-end voxel throughput of a
  range-leased downsample campaign under the closed-loop driver — a
  clean run vs a hostile one where a live range holder is frozen
  mid-lease (SIGSTOP) and its tail is rescued by straggler speculation
  before the zombie wakes into the fence. Identical task grids and
  fleet policy, so hostile/clean is the measured price of the storm
  WITH survival on. Returns (hostile_voxps, clean_voxps, spec_issued)."""
  import shutil
  import signal
  import tempfile

  from igneous_tpu import task_creation as tc
  from igneous_tpu.observability import autoscale, campaign, fleet, health
  from igneous_tpu.observability import journal as journal_mod
  from igneous_tpu.queues import FileQueue
  from igneous_tpu.tasks import SleepTask
  from igneous_tpu.volume import Volume

  edge = 96 if QUICK else 128
  img = np.random.default_rng(17).integers(
    0, 255, (edge, edge, 64)
  ).astype(np.uint8)
  n_sleeps = 8 if QUICK else 16

  def run_campaign(root, hostile):
    layer = f"file://{root}/layer"
    Volume.from_numpy(img, layer, chunk_size=(32, 32, 32), compress="gzip")
    tasks = list(tc.create_downsampling_tasks(
      layer, mip=0, num_mips=1, memory_target=int(6e5), compress="gzip",
    ))
    # interleaved SleepTasks stretch the campaign across enough driver
    # ticks for the freeze to land mid-range (same trick as the soak)
    tasks += [SleepTask(seconds=0.4) for _ in range(n_sleeps)]
    spec = f"fq://{root}/q"
    prev_shards = knobs.raw("IGNEOUS_QUEUE_SHARDS")
    os.environ["IGNEOUS_QUEUE_SHARDS"] = "3"
    try:
      q = FileQueue(spec, max_deliveries=25)
      n_tasks = q.insert_batch(tasks, total=len(tasks))
    finally:
      if prev_shards is None:
        os.environ.pop("IGNEOUS_QUEUE_SHARDS", None)
      else:
        os.environ["IGNEOUS_QUEUE_SHARDS"] = prev_shards
    jpath = journal_mod.journal_path_for(q, spec)
    env = {
      "JAX_PLATFORMS": "cpu",
      "PYTHONPATH": (
        _REPO_DIR + os.pathsep + os.environ["PYTHONPATH"]
        if os.environ.get("PYTHONPATH") else _REPO_DIR
      ),
      "IGNEOUS_JOURNAL_FLUSH_SEC": "0.2",
      "IGNEOUS_STEAL": "1",
      "IGNEOUS_STEAL_MIN_HELD_SEC": "1.0",
      "IGNEOUS_SPECULATE_MIN_HELD_SEC": "0",
    }
    actuator = autoscale.LocalPoolActuator(
      spec, worker_args=["--lease-sec", "20", "--batch", "4"],
      env=env, grace_sec=60.0,
    )
    runner = campaign.CampaignRunner(
      jpath, q, actuator,
      policy=autoscale.AutoscalePolicy(
        min_workers=2, max_workers=3, horizon_sec=5.0,
        hysteresis=0.2, cooldown_sec=1.0, step_max=2,
      ),
      health_config=health.HealthConfig(stall_sec=3.0),
      tick_sec=1.0, speculate=True, max_wall_sec=120.0,
    )
    state = {"tick": 0, "stalled": 0, "stopped": None, "resume_at": 0}

    def chaos_sleep(dt):
      state["tick"] += 1
      actuator.reap()
      procs = [p for p in actuator.procs if p.poll() is None]
      if procs and not state["stalled"]:
        holders = set()
        for r in q.range_leases():
          h = r.get("holder") or ""
          if not r.get("expired") and "-" in h:
            try:
              holders.add(int(h.rsplit("-", 1)[1]))
            except ValueError:
              pass
        victims = [p for p in procs if p.pid in holders]
        if victims:
          victims[0].send_signal(signal.SIGSTOP)
          state.update(stalled=1, stopped=victims[0],
                       resume_at=state["tick"] + 6)
      if state["stopped"] is not None and state["tick"] >= state["resume_at"]:
        state["stopped"].send_signal(signal.SIGCONT)
        state["stopped"] = None
      time.sleep(dt)

    prev_spec = knobs.raw("IGNEOUS_SPECULATE_MIN_HELD_SEC")
    os.environ["IGNEOUS_SPECULATE_MIN_HELD_SEC"] = "0"
    try:
      runner.run(sleep_fn=chaos_sleep if hostile else time.sleep)
    finally:
      if state["stopped"] is not None:
        state["stopped"].send_signal(signal.SIGCONT)
      if prev_spec is None:
        os.environ.pop("IGNEOUS_SPECULATE_MIN_HELD_SEC", None)
      else:
        os.environ["IGNEOUS_SPECULATE_MIN_HELD_SEC"] = prev_spec
    assert q.completed == n_tasks, (
      f"completions drifted: tally={q.completed} tasks={n_tasks}"
    )
    if hostile:
      assert state["stalled"], "freeze never landed: hostile == clean"
    records = fleet.load_effective(jpath)
    task_spans = [
      r for r in records
      if r.get("kind") == "span" and r.get("name") == "task"
    ]
    # completions-tally mtime is the instant the last FIRST-resolution
    # landed; the waking zombie's fenced acks never touch it
    makespan = (
      os.path.getmtime(os.path.join(q.path, "completions"))
      - min(r["ts"] for r in task_spans)
    )
    counters = fleet.status(records)["counters"]
    return img.size / max(makespan, 1e-9), counters

  root = tempfile.mkdtemp(prefix="bench_campaign_")
  try:
    clean_rate, _ = run_campaign(os.path.join(root, "clean"), hostile=False)
    hostile_rate, counters = run_campaign(
      os.path.join(root, "hostile"), hostile=True
    )
  finally:
    shutil.rmtree(root, ignore_errors=True)
  return (
    round(hostile_rate, 1), round(clean_rate, 1),
    int(counters.get("speculation.issued", 0)),
  )


def _skip(reason: str) -> dict:
  """Explicit not-run marker (ISSUE 6 satellite): a gated metric records
  WHY it has no number, so the BENCH trajectory distinguishes "skipped
  on this platform" from "measured zero" — a silent null poisoned the
  pool_ab/ccl_relax history for five rounds."""
  return {"skipped": reason}


def _null_check(result: dict):
  """Self-check (ISSUE 11 satellite): no metric in the artifact may be a
  bare null. Every gated metric must carry a ``{"skipped": reason}``
  marker instead — a bare null is indistinguishable from "measured zero"
  or "crashed silently" in the BENCH trajectory. Offending paths are
  rewritten to explicit markers and reported under detail.null_check so
  the regression is loud in the artifact itself, not just absent."""
  offenders = []

  def walk(node, path):
    if isinstance(node, dict):
      for k, v in node.items():
        if v is None:
          offenders.append(f"{path}.{k}" if path else str(k))
          node[k] = _skip("bare null caught by self-check")
        else:
          walk(v, f"{path}.{k}" if path else str(k))
    elif isinstance(node, list):
      for i, v in enumerate(node):
        if v is None:
          offenders.append(f"{path}[{i}]")
          node[i] = _skip("bare null caught by self-check")
        else:
          walk(v, f"{path}[{i}]")

  walk(result, "")
  result.setdefault("detail", {})["null_check"] = (
    "ok" if not offenders else {"bare_nulls_rewritten": offenders}
  )
  return result


def run_bench(platform: str):
  if platform == "tpu":
    # Never report CPU numbers as TPU: a fast axon-init failure silently
    # falls back to cpu ("axon,cpu" platform list), rc stays 0.
    import jax

    backend = jax.default_backend()
    assert backend in ("axon", "tpu"), f"tpu child got backend {backend!r}"
  img, seg = make_data()
  dev_kernel = bench_device_kernels(img, seg)
  host_kernel = None if platform == "tpu" else bench_host_kernels(img, seg)
  cpu1, baseline_kind = bench_cpu_kernels(img, seg)

  # Consistency guard (round-3 postmortem): on the CPU-fallback path the
  # headline (threaded native pooling) and cpu_1core (the same kernels,
  # one core) are measured seconds apart in the same process. The headline
  # dropping below cpu_1core/4 is physically impossible without external
  # interference — the r03 artifact recorded exactly that (21.5M headline
  # vs 1.09G cpu_1core) and poisoned the round's official signal. Discard
  # and re-measure instead of publishing a contended sample.
  guard_retries = 0
  while (
    host_kernel is not None
    and host_kernel < cpu1 / 4
    and guard_retries < 3
  ):
    guard_retries += 1
    time.sleep(3)  # let whatever is contending drain
    host_kernel = bench_host_kernels(img, seg)

  cpu8 = cpu1 * 8.0
  e2e_serial, e2e = bench_e2e(img, seg)
  trace_overhead_pct, stage_spans = bench_trace_overhead(img, seg)
  integrity_overhead_pct = bench_integrity_overhead(img, seg)
  e2e_batched, e2e_batched_device, batched_path = bench_e2e_batched(img, seg)
  inflate = measure_inflate_MBps(seg)
  up, down = measure_transfer_MBps()
  mesh_rate = bench_mesh_kernel()
  ccl_rate = bench_ccl_kernel("scan")
  # ISSUE 10 satellite: on the CPU fallback the batch entry point ignores
  # the algo knob (native short-circuit) — force the device backend so
  # the relax kernel itself is what gets timed
  ccl_relax_rate = bench_ccl_kernel(
    "relax", force_device=(platform != "tpu")
  )
  infer_e2e_rate, infer_busy_ratio = bench_infer()
  if platform == "tpu":
    pool_ab = bench_pool_ab()
    if pool_ab is None:
      # no pallas on this device: fall back to the generic device-vs-host
      # A/B so TPU rounds stop recording a skip here too
      pool_ab = bench_pool_ab_cpu(img)
    if pool_ab is None:
      pool_ab = _skip("pallas unavailable and <2 devices for the A/B")
  else:
    pool_ab = bench_pool_ab_cpu(img)
    if pool_ab is None:
      pool_ab = _skip("single-device host: no device path to A/B")
  edt_rate = bench_edt_kernel()
  edt_device_rate = bench_edt_device_kernel()
  mesh_extract_rate = bench_mesh_extract_kernel()
  pyramid_fused_rate = bench_pyramid_fused(img)
  ragged_batched_rate, ragged_solo_rate, pad_waste_pct = bench_ragged()
  cache_pair = bench_compile_cache()
  tune_ratio = bench_tune()
  mesh_forge_rate, skel_forge_rate = bench_forge_pipelines()
  codec_tbl = bench_codecs(img, seg)
  cseg_speedup = bench_cseg_speedup()
  (queue_enqueue_rate, queue_lease_rate,
   queue_status_ms, queue_classic_rate) = bench_queue()
  (campaign_hostile_rate, campaign_clean_rate,
   campaign_spec_issued) = bench_campaign_survival()
  xfer_passthrough, xfer_decode = bench_transfer_passthrough(seg)
  serve_stats = bench_serve(seg)
  serve_fleet_stats = bench_serve_fleet(seg)

  # Headline = the framework's production kernel path on this platform:
  # device pyramid on TPU; on the CPU fallback, the native threaded host
  # path that downsample_auto actually dispatches to here (the XLA-CPU
  # device-kernel rate stays in detail for reference).
  headline = dev_kernel if host_kernel is None else host_kernel
  result = {
    "metric": "downsample_kernel_mip0to4_voxels_per_sec",
    "value": round(headline, 1),
    "unit": "vox/s",
    "vs_baseline": round(headline / cpu8, 3),
    # vs_baseline divides by an 8-CORE credit regardless of how many
    # cores this host actually has; on the 1-core relay host that reads
    # as a 60x miss when the per-core truth is parity. Standalone
    # readers of BENCH_r*.json need both numbers (VERDICT r4 item 6).
    "vs_baseline_per_core": round(headline / cpu1, 3),
    "vs_baseline_note": (
      "vs_baseline uses an 8-core-credit denominator (cpu_1core x 8) on "
      f"a {len(os.sched_getaffinity(0))}-core host; vs_baseline_per_core "
      "divides by the measured single-core rate"
    ),
    "detail": {
      "img_shape": list(IMG_SHAPE),
      "seg_shape": list(SEG_SHAPE),
      "device_kernel_voxps": round(dev_kernel, 1),
      "host_native_kernel_voxps": (
        round(host_kernel, 1) if host_kernel is not None
        else _skip(
          "tpu platform: device pyramid is the production path"
          if platform == "tpu"
          else "native pooling library unavailable on this host"
        )
      ),
      # the baseline credits the reference with 8 cores; on a smaller
      # fallback host the per-core ratio is the informative comparison
      "host_cores": len(os.sched_getaffinity(0)),
      "load_avg": [round(x, 2) for x in os.getloadavg()],
      "best_of_n": BEST_OF_N,
      "raw_samples": _sample_stats(),
      "guard_retries": guard_retries,
      "cpu_1core_kernel_voxps": round(cpu1, 1),
      "cpu8_baseline_voxps": round(cpu8, 1),
      # e2e_pipeline = the production path (staged pipeline ON);
      # e2e_serial = the same stream strictly per-task serial (what
      # r01-r05 measured under this key's name)
      "e2e_pipeline_voxps": round(e2e, 1),
      "e2e_serial_voxps": round(e2e_serial, 1),
      "pipeline_speedup": round(e2e / e2e_serial, 3),
      "pipeline_threads_active": __import__(
        "igneous_tpu.pipeline.config", fromlist=["config"]
      ).use_threads(),
      "inflate_MBps": inflate,
      # ISSUE 5: span recording cost at default sampling (negative =
      # measurement noise on a shared host) + where the traced run's
      # wall time went, by span name
      "trace_overhead_pct": trace_overhead_pct,
      "stage_spans": stage_spans,
      # ISSUE 16: clean-path cost of the checksummed write envelope
      # (digest per put + manifest flushes) vs IGNEOUS_INTEGRITY=off;
      # acceptance gate is <=5% (negative = host drift noise)
      "integrity_overhead_pct": integrity_overhead_pct,
      "e2e_batched_voxps": round(e2e_batched, 1),
      "e2e_batched_device_voxps": (
        round(e2e_batched_device, 1) if e2e_batched_device
        else _skip("no device mesh/pool available for the batched path")
      ),
      "e2e_batched_path": batched_path,
      "transfer_MBps_up_down": [up, down],
      "mesh_count_kernel_voxps": round(mesh_rate, 1),
      "mesh_forge_e2e_voxps": mesh_forge_rate,
      "skeleton_forge_csa_e2e_voxps": skel_forge_rate,
      "ccl_kernel_voxps": round(ccl_rate, 1),
      "ccl_relax_kernel_voxps": (
        round(ccl_relax_rate, 1) if ccl_relax_rate is not None
        else _skip("relax kernel produced no measurement")
      ),
      # ISSUE 10: conv-net inference as a first-class workload — e2e
      # voxels/s through the staged pipeline and the fraction of the
      # campaign the device spent computing (ledger busy delta / wall)
      "infer_e2e_voxps": round(infer_e2e_rate, 1),
      "infer_device_busy_ratio": (
        round(infer_busy_ratio, 4) if infer_busy_ratio is not None
        else _skip("zero-wall inference window")
      ),
      # ISSUE 4: compressed-domain fast paths
      "codec_MBps": codec_tbl,
      "cseg_vs_loop": cseg_speedup,
      # ISSUE 15: batched queue wire protocol + range leases — segment
      # enqueue and range-lease acquisition rates on a 100k-task fq://
      # campaign, the classic per-task enqueue for the speedup
      # denominator, and the depth read (O(shards), not O(tasks))
      "queue_enqueue_tasks_per_sec": queue_enqueue_rate,
      "queue_lease_tasks_per_sec": queue_lease_rate,
      "queue_status_ms_100k": queue_status_ms,
      "queue_classic_enqueue_tasks_per_sec": queue_classic_rate,
      "queue_enqueue_speedup": (
        round(queue_enqueue_rate / queue_classic_rate, 1)
        if queue_classic_rate else _skip("classic enqueue measured zero")
      ),
      # ISSUE 17: campaign survival — identical range-leased downsample
      # campaigns under the closed-loop driver, clean vs hostile (a
      # range holder frozen mid-lease, tail rescued by speculation);
      # the ratio is the storm's measured throughput tax with survival on
      "campaign_hostile_voxps": campaign_hostile_rate,
      "campaign_clean_voxps": campaign_clean_rate,
      "campaign_survival_retention": (
        round(campaign_hostile_rate / campaign_clean_rate, 3)
        if campaign_clean_rate
        else _skip("clean campaign measured zero")
      ),
      "campaign_speculation_issued": campaign_spec_issued,
      "transfer_passthrough_voxps": xfer_passthrough,
      "transfer_decode_voxps": xfer_decode,
      "transfer_passthrough_speedup": (
        round(xfer_passthrough / xfer_decode, 2) if xfer_decode
        else _skip("decode-path transfer rate unavailable")
      ),
      "edt_kernel_voxps": round(edt_rate, 1),
      # ISSUE 11: the device kernel suite measured AS device kernels —
      # backend pins keep CPU-fallback rounds from silently substituting
      # the host paths (see each bench's docstring)
      "edt_device_kernel_voxps": round(edt_device_rate, 1),
      "mesh_extract_kernel_voxps": round(mesh_extract_rate, 1),
      "pyramid_fused_voxps": round(pyramid_fused_rate, 1),
      # ISSUE 12: a mixed-shape ragged fleet, paged (ONE compiled
      # signature for the whole campaign) vs solo per-cutout (a compile
      # per distinct shape) — both cold, because the recompile tax is
      # the thing being removed — plus the page slack the campaign paid
      "ragged_batched_voxps": round(ragged_batched_rate, 1),
      "ragged_solo_voxps": round(ragged_solo_rate, 1),
      "pad_waste_pct": (
        pad_waste_pct if pad_waste_pct is not None
        else _skip("no pad-waste bytes recorded during the paged run")
      ),
      # ISSUE 19: the per-worker startup tax the persistent compile
      # cache removes — the same paged workload in two fresh
      # interpreters sharing a file:// cache, compile seconds paid cold
      # vs fetch seconds paid warm
      "compile_cache_cold_s": (
        round(cache_pair[0], 4) if cache_pair
        else _skip("compile cache children failed or cache never engaged")
      ),
      "compile_cache_warm_s": (
        round(cache_pair[1], 4) if cache_pair
        else _skip("compile cache children failed or cache never engaged")
      ),
      "compile_cache_speedup": (
        round(cache_pair[0] / cache_pair[1], 2)
        if cache_pair and cache_pair[1] > 0
        else _skip("warm child paid ~zero; ratio undefined")
      ),
      # ISSUE 19: budget-bounded autotune sweep on this backend — <1.0
      # means a candidate beat the registry defaults (byte-identity
      # asserted per candidate inside the sweep)
      "tune_best_vs_default_ratio": (
        tune_ratio if tune_ratio is not None
        else _skip("tune sweep failed or measured nothing")
      ),
      "pool_ab": pool_ab,
      # ISSUE 9: interactive serving tier — hot-path latency, sustained
      # keep-alive throughput, and herd-coalescing effectiveness
      **serve_stats,
      **serve_fleet_stats,
      # ISSUE 7: the device telemetry plane's own view of this bench run
      # — per-kernel compile/execute seconds + vox/s, per-device busy
      # seconds, recompile count, transfer bytes, utilization ratio
      "device_telemetry": _device_telemetry(),
      "baseline": baseline_kind + " (reference stack not installed here)",
      "platform": platform,
      "device": _device_name(),
    },
  }
  print(json.dumps(_null_check(result)))


def _device_telemetry():
  from igneous_tpu.observability import device as device_mod

  snap = device_mod.LEDGER.snapshot()
  return snap if snap is not None else _skip("no device dispatches ran")


def _device_name():
  try:
    import jax

    return str(jax.devices()[0])
  except Exception:
    return "unknown"


if __name__ == "__main__":
  if "--child" in sys.argv:
    run_bench(sys.argv[sys.argv.index("--child") + 1])
  else:
    supervise()
