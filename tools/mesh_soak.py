"""512^3 mesh-forge soak — committed generator (same rationale as
tools/skel_soak.py: round-4's ad-hoc fixture was lost with its session,
so cross-round wall numbers start fresh at the round-5 row in
BASELINE.md). Shares skel_soak's grid-placed non-overlapping blob field;
runs 8 sharded MeshTasks (shape 256^3, spatial index) and reports the
fg rate.

Run: PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python tools/mesh_soak.py
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from skel_soak import build_fixture  # noqa: E402


def main():
  from igneous_tpu import task_creation as tc
  from igneous_tpu.storage import clear_memory_storage
  from igneous_tpu.volume import Volume

  seg = build_fixture()
  fg = int((seg != 0).sum())
  print(f"fg: {fg}", flush=True)
  clear_memory_storage()
  Volume.from_numpy(
    seg, "mem://soak/mesh", resolution=(16, 16, 40),
    chunk_size=(128, 128, 128), layer_type="segmentation",
  )
  tasks = list(tc.create_meshing_tasks(
    "mem://soak/mesh", mip=0, shape=(256, 256, 256), sharded=True,
    spatial_index=True,
  ))
  print(f"tasks: {len(tasks)}", flush=True)
  t0 = time.time()
  for t in tasks:
    t.execute()
  dt = time.time() - t0
  print(f"SOAK wall: {dt:.1f}s  fg-rate: {fg / dt / 1e3:.1f} kvox-fg/s  "
        f"load={os.getloadavg()}")


if __name__ == "__main__":
  main()
