"""Device kernel parity smoke (ISSUE 11 CI step).

Runs each PR-11 device kernel on an 8-virtual-device CPU mesh and
asserts the acceptance criteria end to end:

  * byte identity vs the host/native reference per kernel:
      - ccl.tiled[scan]        vs the native C++ union-find NUMBERING
      - mesh.mc_emit           vs host fancy-indexed triangle emission
      - pooling.fused_pyramid  vs the per-level XLA pyramid walk
      - edt.sq_blocked         bitwise-deterministic across runs with
        background exactly zero, and matching the host envelope to float
        tolerance (host and device order the parabola arithmetic
        differently; EDT's byte-identity contract is per-backend)
  * every kernel's device.execute span landed in the journal;
  * the journal's recompile ledger carries an entry per kernel, with
    recompiles never exceeding distinct signatures.

Usage: python tools/kernel_smoke.py
"""

import os
import sys
import tempfile

# must precede the first jax import: the virtual mesh is a backend flag
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["IGNEOUS_TRACE_SAMPLE"] = "1"
os.environ.pop("AXON_POOL_SVC_OVERRIDE", None)
os.environ.pop("AXON_LOOPBACK_RELAY", None)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np

EXPECTED_KERNELS = (
  "ccl.tiled[scan]",
  "edt.sq_blocked",
  "mesh.mc_emit",
  "pooling.fused_pyramid[average]",
)


def check_ccl(rng):
  from igneous_tpu.ops import ccl as ccl_mod

  batch = np.stack([
    ((rng.random((24, 20, 12)) < 0.55)
     * rng.integers(1, 4, (24, 20, 12))).astype(np.uint32)
    for _ in range(8)
  ])
  os.environ["IGNEOUS_CCL_BACKEND"] = "device"
  dev = ccl_mod.connected_components_batch(batch, connectivity=26)
  os.environ["IGNEOUS_CCL_BACKEND"] = "native"
  for k in range(len(batch)):
    nat = ccl_mod.connected_components(batch[k], connectivity=26)
    assert np.array_equal(dev[k], nat), f"ccl chunk {k} numbering differs"
  print("ccl.tiled[scan]: byte-identical to native union-find (8 chunks)")


def check_edt(rng):
  from igneous_tpu.ops import edt as edt_mod

  batch = np.stack([
    ((rng.random((20, 16, 10)) < 0.7)
     * rng.integers(1, 3, (20, 16, 10))).astype(np.uint32)
    for _ in range(8)
  ])
  os.environ["IGNEOUS_EDT_BACKEND"] = "device"
  dev1 = edt_mod.edt_batch(batch, (4.0, 4.0, 40.0))
  dev2 = edt_mod.edt_batch(batch, (4.0, 4.0, 40.0))
  os.environ["IGNEOUS_EDT_BACKEND"] = "numpy"
  for k in range(len(batch)):
    assert np.array_equal(dev1[k], dev2[k]), f"edt chunk {k} nondeterministic"
    assert not dev1[k][batch[k] == 0].any(), f"edt chunk {k} bg nonzero"
    host = edt_mod.edt(batch[k], (4.0, 4.0, 40.0))
    np.testing.assert_allclose(dev1[k], host, rtol=1e-4, atol=1e-3)
  print("edt.sq_blocked: deterministic, zero background, matches host "
        "envelope (8 chunks)")


def check_mesh(rng):
  from igneous_tpu.ops import mesh as mesh_mod

  mask = rng.random((21, 17, 13)) > 0.5
  meshes = {}
  for be in ("host", "device"):
    os.environ["IGNEOUS_MESH_EMIT"] = be
    # twice: the first device call is the fresh-signature compile span;
    # the repeat emits the device.execute span the journal check needs
    meshes[be] = mesh_mod.marching_cubes(mask, anisotropy=(4.0, 4.0, 40.0))
    meshes[be] = mesh_mod.marching_cubes(mask, anisotropy=(4.0, 4.0, 40.0))
  assert np.array_equal(meshes["host"][0], meshes["device"][0]), (
    "mesh vertices differ"
  )
  assert np.array_equal(meshes["host"][1], meshes["device"][1]), (
    "mesh faces differ"
  )
  print(f"mesh.mc_emit: byte-identical to host emission "
        f"({len(meshes['device'][1])} faces)")


def check_pyramid(rng):
  from igneous_tpu.ops import pooling

  img = rng.integers(0, 255, (64, 64, 16)).astype(np.uint8)
  plain = pooling.downsample(img, (2, 2, 1), 3, method="average")
  # twice: first fused call compiles (device.compile span); the repeat
  # emits the device.execute span the journal check needs
  fused = pooling.downsample(
    img, (2, 2, 1), 3, method="average", mip_from=0
  )
  fused = pooling.downsample(
    img, (2, 2, 1), 3, method="average", mip_from=0
  )
  for l in range(3):
    assert np.array_equal(plain[l], fused[l]), f"pyramid mip {l} differs"
  print("pooling.fused_pyramid[average]: byte-identical to the plain walk "
        "(3 mips)")


def main():
  tmp = tempfile.mkdtemp(prefix="igneous-kernel-smoke-")
  jpath = f"file://{tmp}/journal"

  import jax

  assert jax.device_count() == 8, (
    f"expected the 8-virtual-device mesh, got {jax.device_count()}"
  )

  from igneous_tpu.observability import device as device_mod
  from igneous_tpu.observability import fleet
  from igneous_tpu.observability.journal import Journal

  device_mod.install()
  journal = Journal(jpath, worker_id="kernel-smoke")

  rng = np.random.default_rng(11)
  check_ccl(rng)
  check_edt(rng)
  check_mesh(rng)
  check_pyramid(rng)

  assert journal.flush(event="kernel-smoke"), "journal flush wrote nothing"

  records = fleet.load(jpath)
  spans = [r for r in records if r.get("kind") == "span"]
  execs = [s for s in spans if s.get("name") == "device.execute"]
  exec_kernels = {s.get("kernel") for s in execs}
  for kernel in EXPECTED_KERNELS:
    assert kernel in exec_kernels, (
      f"no device.execute span for {kernel} in the journal "
      f"(saw {sorted(exec_kernels)})"
    )

  ledgers = device_mod.device_ledgers(records)
  assert ledgers, "no device ledger records in the journal"
  ledger = next(iter(ledgers.values()))
  kernels = ledger["kernels"]
  for kernel in EXPECTED_KERNELS:
    assert kernel in kernels, (
      f"recompile ledger lacks {kernel} (saw {sorted(kernels)})"
    )
  assert ledger["recompiles"] >= len(EXPECTED_KERNELS)
  assert ledger["recompiles"] <= ledger["distinct_signatures"], (
    "recompiles must count distinct signatures only"
  )
  print(f"journal: {len(execs)} device.execute spans, "
        f"ledger kernels={sorted(kernels)} "
        f"recompiles={ledger['recompiles']}")
  print("KERNEL_SMOKE_OK")


if __name__ == "__main__":
  main()
