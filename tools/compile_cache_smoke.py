"""Fleet compile cache warm-start smoke (ISSUE 19 CI step).

Two FRESH worker subprocesses share one ``file://`` compile cache and
run the same ragged (paged) downsample campaign on identical seeded
volumes. Worker 1 pays the XLA compiles and publishes executables;
worker 2 must warm-start:

  * >= 1 ``device.compile_cache.hit`` span per paged kernel in worker
    2's journal;
  * ZERO ``device.compile`` spans in worker 2's journal for any
    (kernel, signature) worker 1 published — asserted against the
    cache's own ``executables/<kernel>/`` listing;
  * zero ``device.recompiles`` in worker 2's ledger for those shared
    kernels (the hit enters the seen-set without a recompile tick);
  * the two campaigns' stored chunks are byte-identical;
  * ``igneous fleet devices`` exits 0 and reports the fleet-wide
    compile-seconds-saved rollup.

Writes the headline numbers to --report-out (CI artifact).

Usage: python tools/compile_cache_smoke.py [--size 250]
       [--report-out compile-cache-report.json]
"""

import argparse
import hashlib
import json
import os
import subprocess
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

PAGED_KERNEL_PREFIXES = ("pooling.paged_pyramid[",)


def worker_env(cache_root):
  env = dict(os.environ)
  env.update({
    "JAX_PLATFORMS": "cpu",
    "PALLAS_AXON_POOL_IPS": "",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    "IGNEOUS_POOL_HOST": "0",        # device pyramid, not native host pool
    "IGNEOUS_PIPELINE": "1",
    "IGNEOUS_PIPELINE_THREADS": "1",
    "IGNEOUS_JOURNAL_FLUSH_SEC": "2",
    "IGNEOUS_TRACE_SAMPLE": "1",
    "IGNEOUS_COMPILE_CACHE": cache_root,
  })
  env.pop("AXON_POOL_SVC_OVERRIDE", None)
  env.pop("AXON_LOOPBACK_RELAY", None)
  return env


def seed_campaign(tmp, tag, data):
  """One volume + one queue of downsample tasks; returns (qspec, jpath,
  volume dir)."""
  from igneous_tpu import task_creation as tc
  from igneous_tpu.queues import FileQueue
  from igneous_tpu.volume import Volume

  path = f"file://{tmp}/img-{tag}"
  Volume.from_numpy(data, path, chunk_size=(32, 32, 32),
                    layer_type="image")
  tasks = list(tc.create_downsampling_tasks(
    path, mip=0, num_mips=1, memory_target=2 * 1024 * 1024
  ))
  assert len(tasks) >= 4, f"want a few tasks, got {len(tasks)}"
  qdir = f"{tmp}/q-{tag}"
  FileQueue(f"fq://{qdir}").insert(tasks)
  return f"fq://{qdir}", f"file://{qdir}/journal", f"{tmp}/img-{tag}"


def run_worker(qspec, env):
  proc = subprocess.run(
    [sys.executable, "-m", "igneous_tpu", "execute", qspec,
     "--batch", "4", "--exit-on-empty", "--min-sec", "10", "-q",
     "--lease-sec", "60"],
    env=env, cwd=REPO, capture_output=True, text=True, timeout=600,
  )
  sys.stdout.write(proc.stdout)
  sys.stderr.write(proc.stderr)
  assert proc.returncode == 0, f"worker failed rc={proc.returncode}"


def journal_view(jpath):
  """(compile span kernels->count, cache-hit span kernels->count,
  merged ledger dict) for one worker's journal."""
  from igneous_tpu.observability import device as device_mod
  from igneous_tpu.observability import fleet

  records = fleet.load(jpath)
  spans = [r for r in records if r.get("kind") == "span"]
  compiles, hits = {}, {}
  for s in spans:
    k = s.get("kernel")
    if s.get("name") == "device.compile":
      compiles[k] = compiles.get(k, 0) + 1
    elif s.get("name") == "device.compile_cache.hit":
      hits[k] = hits.get(k, 0) + 1
  ledgers = device_mod.device_ledgers(records)
  assert ledgers, f"no device ledger records in {jpath}"
  return compiles, hits, next(iter(ledgers.values()))


def volume_digests(vol_dir):
  """rel-path -> content digest for every stored chunk (provenance and
  the integrity manifests carry timestamps/worker ids and are excluded —
  the audit plane has its own tests)."""
  out = {}
  for root, _dirs, files in os.walk(vol_dir):
    for fn in files:
      if "provenance" in fn:
        continue
      full = os.path.join(root, fn)
      rel = os.path.relpath(full, vol_dir)
      if rel.startswith("integrity"):
        continue
      with open(full, "rb") as f:
        out[rel] = hashlib.blake2b(f.read(), digest_size=16).hexdigest()
  return out


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument("--size", type=int, default=250)
  ap.add_argument("--report-out", default=None)
  args = ap.parse_args()

  tmp = tempfile.mkdtemp(prefix="igneous-compile-cache-smoke-")
  cache_root = f"file://{tmp}/compile-cache"

  from igneous_tpu import compile_cache as cc

  # odd-size layer: the task grid clamps at the edges, so ragged cells
  # ride the paged pyramid — the kernels the warm-start must cover
  rng = np.random.default_rng(19)
  n = args.size
  data = rng.integers(0, 255, (n, n, 64)).astype(np.uint8)

  q1, j1, vol1 = seed_campaign(tmp, "w1", data)
  q2, j2, vol2 = seed_campaign(tmp, "w2", data)
  env = worker_env(cache_root)

  run_worker(q1, env)
  compiles1, hits1, ledger1 = journal_view(j1)
  assert compiles1, "worker 1 journal has no device.compile spans"
  cc1 = ledger1.get("compile_cache") or {}
  assert cc1.get("puts", 0) >= 1, (
    f"worker 1 published nothing to the cache: {cc1}"
  )

  # the cache's own listing is the shared-signature ground truth:
  # executables/<kernel>/<digest>.bin, kernel names sanitize-stable
  exe_dir = os.path.join(tmp, "compile-cache", cc.ENTRY_PREFIX.rstrip("/"))
  shared_kernels = sorted(os.listdir(exe_dir))
  assert shared_kernels, "no executables published under the cache root"
  paged_shared = [
    k for k in shared_kernels
    if any(k.startswith(p) for p in PAGED_KERNEL_PREFIXES)
  ]
  assert paged_shared, (
    f"no paged kernels in the shared cache (saw {shared_kernels})"
  )
  print(f"worker 1: compiled {sorted(compiles1)}, "
        f"published {shared_kernels} ({cc1.get('puts')} puts)")

  run_worker(q2, env)
  compiles2, hits2, ledger2 = journal_view(j2)
  cc2 = ledger2.get("compile_cache") or {}

  # warm start: worker 2 never XLA-compiles a published signature
  overlap = sorted(set(compiles2) & set(shared_kernels))
  assert not overlap, (
    f"worker 2 recompiled shared kernels {overlap} — "
    f"compile spans {compiles2}"
  )
  for k in paged_shared:
    assert hits2.get(k, 0) >= 1, (
      f"no device.compile_cache.hit span for paged kernel {k} "
      f"in worker 2's journal (hits: {hits2})"
    )
  for k, stats in ledger2.get("kernels", {}).items():
    if k in shared_kernels:
      assert stats.get("compiles", 0) == 0, (
        f"worker 2 ledger shows {stats['compiles']} recompiles for "
        f"shared kernel {k}"
      )
      assert stats.get("cache_hits", 0) >= 1, (k, stats)
  assert cc2.get("hits", 0) >= len(paged_shared), cc2
  print(f"worker 2: {cc2.get('hits')} cache hits, "
        f"{cc2.get('saved_s')}s compile time saved, "
        f"zero recompiles for {shared_kernels}")

  # identical campaign, identical bytes — warm executables must not
  # change a single stored chunk
  d1, d2 = volume_digests(vol1), volume_digests(vol2)
  assert d1 and d1.keys() == d2.keys(), (
    f"chunk sets differ: {sorted(set(d1) ^ set(d2))[:8]}"
  )
  diff = [k for k in d1 if d1[k] != d2[k]]
  assert not diff, f"{len(diff)} chunks differ, e.g. {diff[:8]}"
  print(f"byte-identity: {len(d1)} stored objects identical")

  # fleet rollup: the merged view must surface compile-seconds-saved
  proc = subprocess.run(
    [sys.executable, "-m", "igneous_tpu", "fleet", "devices",
     "--journal", j2],
    env=env, cwd=REPO, capture_output=True, text=True, timeout=120,
  )
  sys.stdout.write(proc.stdout)
  assert proc.returncode == 0, (
    f"igneous fleet devices exited {proc.returncode}: {proc.stderr}"
  )
  assert "compile cache" in proc.stdout, proc.stdout

  report = {
    "shared_kernels": shared_kernels,
    "paged_kernels": paged_shared,
    "worker1_compile_spans": compiles1,
    "worker1_cache": cc1,
    "worker2_compile_spans": compiles2,
    "worker2_hit_spans": hits2,
    "worker2_cache": cc2,
    "compile_seconds_saved": cc2.get("saved_s"),
    "stored_objects_compared": len(d1),
    "byte_identical": True,
  }
  if args.report_out:
    with open(args.report_out, "w") as f:
      json.dump(report, f, indent=2, sort_keys=True)
    print(f"report written to {args.report_out}")

  print("COMPILE_CACHE_SMOKE_OK")


if __name__ == "__main__":
  main()
