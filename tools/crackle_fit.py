"""Crackle resume-rule search harness (round 5; see ROADMAP + probe).

Round 4 pinned everything about the .ckl container and move alphabet
except the '2'/resume micro-rule (tools/crackle_probe.py docstring). This
harness sweeps parameterized decoder VMs over the open semantic choices
and scores each candidate against oracles the fixture itself supplies:

  * cc:        region components of the decoded crack field vs the truth
               the FLAT labels section records per slice;
  * dangling:  interior vertices with drawn-degree 1 — impossible in any
               real label-boundary field (degrees are 0/2/3/4);
  * redraws:   edges drawn twice;
  * full-stream consumption: the real rule ends cleanly (no symbol count
               is stored, so the decode must self-terminate).

ROUND-5 RESULTS (1144 variants swept across three VM families):

1. Family A (round 4's reading: '2' always pushes a junction mark; an
   impossible move pops) — every variant either dies early (cc ~300-550
   with thousands of unread symbols) or overshoots ~2x. REJECTED.
2. Family B discovery: '2' push-vs-pop IS decoder-distinguishable by the
   drawn degree of the current vertex (slice 0, si=162: that '2' lands
   on a degree-3 loop-closure vertex; all five earlier '2's landed on
   degree-1 fresh vertices). Best family-B/C variants consume the whole
   stream with 1-6 dangling and ZERO redraws but plateau at cc ~2x truth
   with ~truth-many single-pixel spurious regions — the signature of one
   pinched corner per resume. Resume-without-draw narrows but does not
   close the gap.
3. CLOSEST YET — travel/pen-up reading: ONE continuous relative-turn
   walk (chir=1: 3 = +90), where '2' flags the following move as
   non-drawing travel ('22' = two moves), off-grid -> next seed. This
   consumes EVERY symbol on z=0/z=511 and lands cc within 3% of truth
   (z=0: 1189/1225, z=511: 1196/1237) — by far the closest full-stream
   decode over four rounds of attempts. Open problems: (a) the decoded
   field has ~one dangling end per hop (2457 for 2454 hops on z=0), so
   the true rule must resolve hop geometry differently (613/2454 hop
   edges do get drawn by other strokes; endpoint degrees are mixed);
   (b) z=1 exhausts its 8 seeds at symbol 17915/29824 under every
   family, pointing at un-modeled trail-start bookkeeping (the still
   unexplained trailing u16 of every seed table: 242/203/228/83/267 for
   z=0/1/2/3/511).

4. SECOND-TIMEBOX ADDENDUM (border-slide): every off-grid attempt in the
   pen-up decode happens exactly AT a border wanting to continue OUT.
   Adding border-slide (an off-grid move turns +-90 to continue along
   the border) makes EVERY tested slice consume its whole stream with
   cc within 1-4% of truth (z=1: 1251/1240, z=256: 1399/1405, z=511:
   1213/1237) while using almost NO seeds (z=1: 1 of 8) — so the
   reference decoder's trail bookkeeping is essentially "one continuous
   walk + border sliding", and the seed table's role remains open
   (trailing u16 is uniform in [0,512] — a coordinate, uncorrelated
   with every per-slice count tested; appending it as an extra seed
   changes nothing). Still open and now sharply posed: (a) the
   ~one-dangling-end-per-hop geometry (true fields have none, so '2'
   cannot be literal pen-up; hop edges drawn by other strokes: only
   613/2454), and (b) best (chir, d0, slide-handedness) still varies
   per slice, so the orientation convention is per-seed/per-situation,
   not global.

5. FINAL round-5 experiments pinned the contradiction precisely. Hop
   windows show '2'-flagged edges bridging ordinary staircase steps —
   visibly REAL boundary edges. Three decode variants triangulate:
   pen-up (skip hop edges): cc within 3% of truth, one dangling end per
   hop; draw-everything: dangling ~10 (geometry closes!) but cc +40%;
   pen-up + draw-only-dangling-adjacent-hops: dangling -> 0-1 but cc
   ~ +60%. No subset of hop edges can satisfy closure AND counts
   simultaneously => the NON-flagged move geometry must also be wrong
   in a compensating way (e.g. '2' shifts which side of the walk the
   crack is drawn on, or moves carry a sub-voxel offset). That is the
   round-6 entry point.

Usage:
  python tools/crackle_fit.py sweep [z]       # family A grid
  python tools/crackle_fit.py sweep2 [z]      # family B grid
  python tools/crackle_fit.py sweep3 [z...]   # family C grid
"""

from __future__ import annotations

import itertools
import os
import sys
import time

import numpy as np
from scipy import ndimage

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from crackle_probe import parse_container, parse_slice  # noqa: E402

FIXTURE = "/root/reference/test/connectomics.npy.ckl.gz"

RESUME_MODES = (
  "auto_abs",      # scan absolute 0..3 for first undrawn (probe's rule)
  "auto_cw",       # scan md, md+1, ... (from the stored mark direction)
  "auto_ccw",      # scan md, md-1, ...
  "auto_cw_rev",   # scan md+2, md+3, ...
  "auto_ccw_rev",  # scan md+2, md+1, ...
  "sym_abs",       # triggering symbol = absolute resume direction
  "sym_rel",       # triggering symbol = turn relative to stored md
  "sym_rel_rev",   # ... relative to reversed stored md
  # branch edge drawn FREE (first undrawn by scan), then the triggering
  # symbol replays as the relative turn AFTER stepping onto the branch —
  # the economy where every non-'2' symbol draws exactly one edge and
  # each resume adds one free edge (see round-5 notes in ROADMAP)
  "autoreplay_abs",
  "autoreplay_cw",
  "autoreplay_ccw",
  "autoreplay_cw_rev",
  "autoreplay_ccw_rev",
)
SEED_MODES = ("abs", "fixed0", "fixed1", "fixed2", "fixed3")


def decode_vm(
  seeds, syms, sx, sy, *,
  chir=False, trigger_redraw=False, resume_mode="auto_abs",
  seed_mode="fixed0", pop_order="lifo",
):
  """Parameterized crack-walk VM. Returns (vcr, hcr, stats)."""
  vcr = np.zeros((sx + 1, sy), bool)
  hcr = np.zeros((sx, sy + 1), bool)
  marks: list = []
  stats = {"redraws": 0, "stuck": 0, "seeds_used": 0, "marks_left": 0,
           "dead_marks": 0}

  def drawn(x, y, d):
    """True/False = edge drawn state; None = off-grid. Plain bools: the
    VM compares with ``is``, and np.bool_(False) is not False."""
    if d == 0:
      return bool(vcr[x, y - 1]) if y - 1 >= 0 else None
    if d == 2:
      return bool(vcr[x, y]) if y <= sy - 1 else None
    if d == 1:
      return bool(hcr[x, y]) if x <= sx - 1 else None
    return bool(hcr[x - 1, y]) if x - 1 >= 0 else None

  def draw(x, y, d):
    if d == 0:
      vcr[x, y - 1] = True
      return x, y - 1
    if d == 2:
      vcr[x, y] = True
      return x, y + 1
    if d == 1:
      hcr[x, y] = True
      return x + 1, y
    hcr[x - 1, y] = True
    return x - 1, y

  n = len(syms)
  si = 0
  ci = 0

  def next_seed(trigger_sym):
    """-> (x, y, d) or None when seeds are exhausted."""
    nonlocal ci, si
    if ci >= len(seeds):
      return None
    x, y = seeds[ci]
    ci += 1
    stats["seeds_used"] += 1
    if seed_mode == "abs":
      if trigger_sym is not None:
        d = int(trigger_sym)
      else:
        if si >= n:
          return None
        d = int(syms[si]); si += 1
    else:
      d = int(seed_mode[-1])
    return x, y, d

  start = next_seed(None)
  if start is None:
    return vcr, hcr, stats
  x, y, d = start

  while si < n:
    s = int(syms[si]); si += 1
    if s == 2:
      marks.append((x, y, d))
      continue
    step = s if not chir or s == 0 else 4 - s
    nd = (d + step) % 4
    st = drawn(x, y, nd)
    if st is False or (st is True and not trigger_redraw):
      if st is True:
        stats["redraws"] += 1
      d = nd
      x, y = draw(x, y, nd)
      continue
    # impossible move: control event — pop marks / advance seeds
    resumed = False
    while marks:
      mx, my, md = marks.pop(-1 if pop_order == "lifo" else 0)
      if resume_mode.startswith(("auto_", "autoreplay_")):
        parts = resume_mode.split("_")
        base, rev = parts[1], parts[-1] == "rev"
        if base == "abs":
          scan = (0, 1, 2, 3)
        elif base == "cw":
          scan = tuple((md + 2 * rev + k) % 4 for k in range(4))
        else:  # ccw
          scan = tuple((md + 2 * rev - k) % 4 for k in range(4))
        rd = next((dd for dd in scan if drawn(mx, my, dd) is False), None)
      else:
        if resume_mode == "sym_abs":
          rd = s
        elif resume_mode == "sym_rel":
          rd = (md + step) % 4
        else:  # sym_rel_rev
          rd = (md + 2 + step) % 4
        if drawn(mx, my, rd) is not False:
          rd = None
      if rd is None:
        stats["dead_marks"] += 1
        continue
      d = rd
      x, y = draw(mx, my, rd)
      resumed = True
      if resume_mode.startswith("autoreplay_"):
        # the branch edge was free; the triggering symbol now replays
        # as the relative turn from the new position/direction
        nd = (d + step) % 4
        st = drawn(x, y, nd)
        if st is False:
          d = nd
          x, y = draw(x, y, nd)
        elif st is True and not trigger_redraw:
          stats["redraws"] += 1
          d = nd
          x, y = draw(x, y, nd)
        else:
          # replay itself impossible: treat as a fresh control event
          # on the next loop round by pushing the state back — simplest
          # faithful behavior is to count it; rare under a correct rule
          stats["replay_failed"] = stats.get("replay_failed", 0) + 1
      break
    if resumed:
      continue
    nxt = next_seed(s)
    if nxt is None:
      stats["stuck"] += 1
      break
    x, y, d = nxt
  stats["marks_left"] = len(marks)
  return vcr, hcr, stats


def decode_vm2(
  seeds, syms, sx, sy, *,
  chir=False, d0=1, pop_style="peek", resume_dir="auto_cw",
  impossible_resumes=True, pop_order="lifo",
):
  """Round-5 family B: '2' is push or pop depending on the DRAWN degree
  of the current vertex — decoder-detectable (arrival edge only = fresh
  junction, push; degree >=3 = loop closure, trail ends, resume).
  Evidence: slice 0 si=162's '2' lands on a degree-3 closure vertex while
  all prior '2's landed on degree-1 fresh vertices."""
  vcr = np.zeros((sx + 1, sy), bool)
  hcr = np.zeros((sx, sy + 1), bool)
  deg = np.zeros((sx + 1, sy + 1), np.int16)
  marks: list = []
  stats = {"pushes": 0, "pops": 0, "impossible": 0, "dead_marks": 0,
           "stuck": 0, "seeds_used": 0, "marks_left": 0, "redraws": 0}

  def drawn(x, y, d):
    if d == 0:
      return bool(vcr[x, y - 1]) if y - 1 >= 0 else None
    if d == 2:
      return bool(vcr[x, y]) if y <= sy - 1 else None
    if d == 1:
      return bool(hcr[x, y]) if x <= sx - 1 else None
    return bool(hcr[x - 1, y]) if x - 1 >= 0 else None

  def draw(x, y, d):
    # degree counts FIRST draws only, so redraw-permitting variants
    # can't inflate (or overflow) the push-vs-pop classification
    fresh = drawn(x, y, d) is False
    if fresh:
      deg[x, y] += 1
    if d == 0:
      vcr[x, y - 1] = True
      nx, ny = x, y - 1
    elif d == 2:
      vcr[x, y] = True
      nx, ny = x, y + 1
    elif d == 1:
      hcr[x, y] = True
      nx, ny = x + 1, y
    else:
      hcr[x - 1, y] = True
      nx, ny = x - 1, y
    if fresh:
      deg[nx, ny] += 1
    return nx, ny

  n = len(syms)
  si = 0
  ci = 0

  def resume():
    """-> (x, y, d) from the mark stack, or None."""
    nonlocal si
    parts = resume_dir.split("_")
    s2 = None  # nextsym modes consume ONE symbol, reused across marks
    while marks:
      idx = len(marks) - 1 if pop_order == "lifo" else 0
      mx, my, md = marks[idx]
      if parts[0] == "auto":
        base, rev = parts[1], parts[-1] == "rev"
        if base == "abs":
          scan = (0, 1, 2, 3)
        elif base == "cw":
          scan = tuple((md + 2 * rev + k) % 4 for k in range(4))
        else:
          scan = tuple((md + 2 * rev - k) % 4 for k in range(4))
        rd = next((dd for dd in scan if drawn(mx, my, dd) is False), None)
        if rd is None:
          del marks[idx]
          stats["dead_marks"] += 1
          continue
        if pop_style == "pop":
          del marks[idx]
        return mx, my, rd
      if s2 is None:
        if si >= n:
          return None
        s2 = int(syms[si]); si += 1
      if parts[1] == "abs":
        rd = s2
      else:
        st2 = s2 if not chir or s2 == 0 else 4 - s2
        rd = (md + st2 + (2 if parts[-1] == "rev" else 0)) % 4
      if drawn(mx, my, rd) is not False:
        del marks[idx]
        stats["dead_marks"] += 1
        continue
      if pop_style == "pop":
        del marks[idx]
      return mx, my, rd
    return None

  x, y = seeds[ci]
  ci += 1
  stats["seeds_used"] += 1
  d = d0

  while si < n:
    s = int(syms[si]); si += 1
    if s == 2:
      # degree counts only drawn edges; at arrival a fresh vertex has 1
      if deg[x, y] <= 1:
        marks.append((x, y, d))
        stats["pushes"] += 1
        continue
      stats["pops"] += 1
      r = resume()
      if r is None:
        if ci < len(seeds):
          x, y = seeds[ci]
          ci += 1
          stats["seeds_used"] += 1
          d = d0
        else:
          stats["stuck"] += 1
          break
      else:
        x, y, d = r
        x, y = draw(x, y, d)
      continue
    step = s if not chir or s == 0 else 4 - s
    nd = (d + step) % 4
    st = drawn(x, y, nd)
    if st is False:
      d = nd
      x, y = draw(x, y, nd)
      continue
    if not impossible_resumes:
      if st is True:
        stats["redraws"] += 1
        d = nd
        x, y = draw(x, y, nd)
        continue
    stats["impossible"] += 1
    r = resume()
    if r is None:
      if ci < len(seeds):
        x, y = seeds[ci]
        ci += 1
        stats["seeds_used"] += 1
        d = d0
      else:
        stats["stuck"] += 1
        break
    else:
      x, y, d = r
      x, y = draw(x, y, d)
  stats["marks_left"] = len(marks)
  return vcr, hcr, stats


def decode_vm3(
  seeds, syms, sx, sy, *,
  chir=True, d0=0, resume_dir="auto_ccw", impossible_resumes=True,
  require_mark=True, draw_on_resume=True,
):
  """Round-5 family C: path-backtracking (round 4's 65% family) refined.
  '2' at a fresh vertex pushes a junction mark; a control event ('2' at a
  closure vertex, or an impossible move) BACKTRACKS along the walked path
  to the most recent vertex that (require_mark) is marked and has an
  undrawn in-grid direction, resuming there."""
  vcr = np.zeros((sx + 1, sy), bool)
  hcr = np.zeros((sx, sy + 1), bool)
  deg = np.zeros((sx + 1, sy + 1), np.int16)
  marked = set()
  path: list = []
  stats = {"pushes": 0, "pops": 0, "impossible": 0, "stuck": 0,
           "seeds_used": 0, "redraws": 0, "syms_left": 0}

  def drawn(x, y, d):
    if d == 0:
      return bool(vcr[x, y - 1]) if y - 1 >= 0 else None
    if d == 2:
      return bool(vcr[x, y]) if y <= sy - 1 else None
    if d == 1:
      return bool(hcr[x, y]) if x <= sx - 1 else None
    return bool(hcr[x - 1, y]) if x - 1 >= 0 else None

  def draw(x, y, d):
    # degree counts FIRST draws only, so redraw-permitting variants
    # can't inflate (or overflow) the push-vs-pop classification
    fresh = drawn(x, y, d) is False
    if fresh:
      deg[x, y] += 1
    if d == 0:
      vcr[x, y - 1] = True
      nx, ny = x, y - 1
    elif d == 2:
      vcr[x, y] = True
      nx, ny = x, y + 1
    elif d == 1:
      hcr[x, y] = True
      nx, ny = x + 1, y
    else:
      hcr[x - 1, y] = True
      nx, ny = x - 1, y
    if fresh:
      deg[nx, ny] += 1
    return nx, ny

  def scan_dir(mx, my, md):
    parts = resume_dir.split("_")
    base, rev = parts[1], parts[-1] == "rev"
    if base == "abs":
      scan = (0, 1, 2, 3)
    elif base == "cw":
      scan = tuple((md + 2 * rev + k) % 4 for k in range(4))
    else:
      scan = tuple((md + 2 * rev - k) % 4 for k in range(4))
    return next((dd for dd in scan if drawn(mx, my, dd) is False), None)

  def backtrack():
    """-> (x, y, rd) or None; walks path backwards."""
    while path:
      px, py, pd = path[-1]
      eligible = (not require_mark) or ((px, py) in marked)
      if eligible:
        rd = scan_dir(px, py, pd)
        if rd is not None:
          return px, py, rd
      path.pop()
    return None

  n = len(syms)
  si = 0
  ci = 0
  x, y = seeds[ci]
  ci += 1
  stats["seeds_used"] += 1
  d = d0
  path.append((x, y, d))

  while si < n:
    s = int(syms[si]); si += 1
    if s == 2:
      if deg[x, y] <= 1:
        marked.add((x, y))
        stats["pushes"] += 1
        continue
      stats["pops"] += 1
      r = backtrack()
      if r is None:
        if ci < len(seeds):
          x, y = seeds[ci]; ci += 1
          stats["seeds_used"] += 1
          d = d0
          path.append((x, y, d))
        else:
          stats["stuck"] += 1
          break
      else:
        mx, my, rd = r
        d = rd
        if draw_on_resume:
          x, y = draw(mx, my, rd)
        else:
          x, y = mx, my
        path.append((x, y, d))
      continue
    step = s if not chir or s == 0 else 4 - s
    nd = (d + step) % 4
    st = drawn(x, y, nd)
    if st is False:
      d = nd
      x, y = draw(x, y, nd)
      path.append((x, y, d))
      continue
    if not impossible_resumes and st is True:
      stats["redraws"] += 1
      d = nd
      x, y = draw(x, y, nd)
      path.append((x, y, d))
      continue
    stats["impossible"] += 1
    r = backtrack()
    if r is None:
      if ci < len(seeds):
        x, y = seeds[ci]; ci += 1
        stats["seeds_used"] += 1
        d = d0
        path.append((x, y, d))
      else:
        stats["stuck"] += 1
        break
    else:
      mx, my, rd = r
      d = rd
      if draw_on_resume:
        x, y = draw(mx, my, rd)
      else:
        x, y = mx, my
      path.append((x, y, d))
  stats["syms_left"] = n - si
  return vcr, hcr, stats


# -- oracles -----------------------------------------------------------------


def region_components(vcr, hcr, sx, sy):
  """Pixel components of the crack field + the label array (scan-order
  component ids, scipy numbering) — expanded-grid trick, one C pass."""
  grid = np.zeros((2 * sx + 1, 2 * sy + 1), bool)
  grid[1::2, 1::2] = True
  grid[2:-1:2, 1::2] = ~vcr[1:sx, :]
  grid[1::2, 2:-1:2] = ~hcr[:, 1:sy]
  st = np.array([[0, 1, 0], [1, 1, 1], [0, 1, 0]], bool)
  lab, n = ndimage.label(grid, structure=st)
  return lab[1::2, 1::2], n


def dangling_interior(vcr, hcr, sx, sy):
  """Interior vertices with exactly one drawn crack — impossible in a
  real boundary field."""
  deg = np.zeros((sx + 1, sy + 1), np.int16)
  deg[:, 1:] += vcr          # up edge of vertex (x,y) is vcr[x, y-1]
  deg[:, :-1] += vcr         # down edge
  deg[1:, :] += hcr          # left edge
  deg[:-1, :] += hcr         # right edge
  inner = deg[1:sx, 1:sy]
  return int((inner == 1).sum())


def score_slice(c, z, params):
  sx, sy, _ = c["shape"]
  seeds, _trail, syms = parse_slice(c, z)
  vcr, hcr, stats = decode_vm(seeds, syms, sx, sy, **params)
  _lab, n = region_components(vcr, hcr, sx, sy)
  truth = int(c["cc_per_slice"][z])
  dang = dangling_interior(vcr, hcr, sx, sy)
  return {
    "cc": n, "truth": truth, "dcc": abs(n - truth), "dangling": dang,
    **stats,
  }


def sweep(c, z=0):
  rows = []
  t0 = time.time()
  for chir, trig, rmode, smode, porder in itertools.product(
    (False, True), (False, True), RESUME_MODES, SEED_MODES,
    ("lifo", "fifo"),
  ):
    params = dict(chir=chir, trigger_redraw=trig, resume_mode=rmode,
                  seed_mode=smode, pop_order=porder)
    r = score_slice(c, z, params)
    rows.append((r["dcc"], r["dangling"], r["redraws"], params, r))
  rows.sort(key=lambda t: (t[0], t[1], t[2]))
  print(f"sweep z={z}: {len(rows)} combos in {time.time()-t0:.1f}s")
  for dcc, dang, redraws, params, r in rows[:15]:
    pp = (f"chir={int(params['chir'])} trig_redraw="
          f"{int(params['trigger_redraw'])} {params['resume_mode']}/"
          f"{params['seed_mode']}/{params['pop_order']}")
    print(f"  dcc={dcc:5d} dang={dang:5d} redraw={redraws:6d} "
          f"cc={r['cc']:5d}/{r['truth']} stuck={r['stuck']} "
          f"marks_left={r['marks_left']} dead={r['dead_marks']} {pp}")
  return rows


def score_slice2(c, z, params):
  sx, sy, _ = c["shape"]
  seeds, _trail, syms = parse_slice(c, z)
  vcr, hcr, stats = decode_vm2(seeds, syms, sx, sy, **params)
  _lab, n = region_components(vcr, hcr, sx, sy)
  truth = int(c["cc_per_slice"][z])
  dang = dangling_interior(vcr, hcr, sx, sy)
  return {"cc": n, "truth": truth, "dcc": abs(n - truth),
          "dangling": dang, **stats}


def sweep2(c, z=0):
  rows = []
  t0 = time.time()
  combos = itertools.product(
    ((0, 1), (0, 2), (0, 3), (1, 0), (1, 1), (1, 3)),  # viable (chir, d0)
    ("peek", "pop"),
    ("auto_abs", "auto_cw", "auto_ccw", "auto_cw_rev", "auto_ccw_rev",
     "nextsym_abs", "nextsym_rel", "nextsym_rel_rev"),
    (True, False),
    ("lifo", "fifo"),
  )
  for (chir, d0), pstyle, rdir, impres, porder in combos:
    params = dict(chir=bool(chir), d0=d0, pop_style=pstyle,
                  resume_dir=rdir, impossible_resumes=impres,
                  pop_order=porder)
    r = score_slice2(c, z, params)
    rows.append((r["dcc"], r["dangling"], r["redraws"], params, r))
  rows.sort(key=lambda t: (t[0], t[1], t[2]))
  print(f"sweep2 z={z}: {len(rows)} combos in {time.time()-t0:.1f}s")
  for dcc, dang, redraws, params, r in rows[:15]:
    pp = (f"chir={int(params['chir'])} d0={params['d0']} "
          f"{params['pop_style']}/{params['resume_dir']}/"
          f"imp={int(params['impossible_resumes'])}/{params['pop_order']}")
    print(f"  dcc={dcc:5d} dang={dang:5d} redraw={redraws:6d} "
          f"cc={r['cc']:5d}/{r['truth']} push={r['pushes']} "
          f"pop={r['pops']} imp={r['impossible']} dead={r['dead_marks']} "
          f"left={r['marks_left']} stuck={r['stuck']} {pp}")
  return rows


if __name__ == "__main__":
  with open(FIXTURE, "rb") as f:
    c = parse_container(f.read())
  mode = sys.argv[1] if len(sys.argv) > 1 else "sweep"
  if mode == "sweep":
    z = int(sys.argv[2]) if len(sys.argv) > 2 else 0
    sweep(c, z)
  elif mode == "sweep2":
    z = int(sys.argv[2]) if len(sys.argv) > 2 else 0
    sweep2(c, z)
  elif mode == "sweep3":
    zs = [int(v) for v in sys.argv[2:]] or [0, 1]
    rows = []
    t0 = time.time()
    for (chir, d0), rdir, impres, reqm, dor in itertools.product(
      ((0, 1), (0, 2), (0, 3), (1, 0), (1, 1), (1, 3)),
      ("auto_abs", "auto_cw", "auto_ccw", "auto_cw_rev", "auto_ccw_rev"),
      (True, False), (True, False), (True, False),
    ):
      params = dict(chir=bool(chir), d0=d0, resume_dir=rdir,
                    impossible_resumes=impres, require_mark=reqm,
                    draw_on_resume=dor)
      tot_dcc = tot_dang = tot_red = tot_left = 0
      per = []
      for z in zs:
        sx, sy, _ = c["shape"]
        seeds, _t, syms = parse_slice(c, z)
        vcr, hcr, st = decode_vm3(seeds, syms, sx, sy, **params)
        _l, n = region_components(vcr, hcr, sx, sy)
        truth = int(c["cc_per_slice"][z])
        dang = dangling_interior(vcr, hcr, sx, sy)
        tot_dcc += abs(n - truth)
        tot_dang += dang
        tot_red += st["redraws"]
        tot_left += st["syms_left"]
        per.append(f"{n}/{truth}")
      rows.append((tot_dcc, tot_dang, tot_left, params, per))
    rows.sort(key=lambda t: (t[0] + 10 * t[1] + t[2],))
    print(f"sweep3 zs={zs}: {len(rows)} combos in {time.time()-t0:.1f}s")
    for dcc, dang, left, params, per in rows[:12]:
      pp = (f"chir={int(params['chir'])} d0={params['d0']} "
            f"{params['resume_dir']}/imp={int(params['impossible_resumes'])}"
            f"/mark={int(params['require_mark'])}"
            f"/dor={int(params['draw_on_resume'])}")
      print(f"  dcc={dcc:5d} dang={dang:4d} syms_left={left:6d} "
            f"cc={per} {pp}")
