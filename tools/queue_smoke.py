#!/usr/bin/env python
"""Queue scale-out smoke (ISSUE 15): batched wire protocol + range leases.

CI acceptance in three acts:

1. **scale** — enqueue a 100k-task campaign through the batched wire
   protocol and gate the rate at >= 20k tasks/s AND >= 10x the classic
   one-file-per-task baseline; `igneous queue status` must answer from
   O(shards) control-plane files (counted, capped) in bounded wall time
   without listing per-task objects;
2. **chaos** — the same downsample campaign run classic-per-task vs
   range-leased under a stale-lease storm (leases expire mid-flight,
   zombie acks fenced) plus a preempt-style drain (one member acked, one
   nacked, the rest released mid-range): output bytes identical,
   completions tally == task count, DLQ empty;
3. **sim** — mine the range-leased campaign's journal (range_sizes must
   be present), re-simulate it with `IGNEOUS_SIM_RANGE_LEASE` semantics,
   and require the predicted completion time within +/-20% of the
   measured wall-clock, bit-identical across same-seed reruns.

Writes queue-report.json next to the CWD for the CI artifact upload.
Exit 0 = all gates passed.
"""

import hashlib
import json
import os
import shutil
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

import numpy as np  # noqa: E402
from click.testing import CliRunner  # noqa: E402

from igneous_tpu import task_creation as tc  # noqa: E402
from igneous_tpu.analysis import discovery  # noqa: E402
from igneous_tpu.cli import main as cli_main  # noqa: E402
from igneous_tpu.observability import replay, sim  # noqa: E402
from igneous_tpu.queues import (  # noqa: E402
  FileQueue,
  PrintTask,
  TaskQueue,
  serialize,
)
from igneous_tpu.tasks import SleepTask  # noqa: E402
from igneous_tpu.volume import Volume  # noqa: E402

SCALE_TASKS = 100_000
ENQUEUE_RATE_GATE = 20_000       # tasks/s, absolute floor
SPEEDUP_GATE = 10.0              # vs the classic per-task layout
BASELINE_TASKS = 2_000
STATUS_WALL_SEC = 2.0            # `queue status` on the 100k queue
QUEUE_FILES_CAP = 256            # control-plane objects for 100k tasks
SEED = 1234
SIM_TASKS = 48
SIM_SLEEP_SEC = 0.02
SIM_BATCH = 4
TOLERANCE = 0.20

report = {"gates": {}, "ok": False}
failures = []


def gate(name, ok, **detail):
  report["gates"][name] = {"ok": bool(ok), **detail}
  status = "PASS" if ok else "FAIL"
  print(f"[queue_smoke] {status} {name}: {detail}")
  if not ok:
    failures.append(name)


def journal_digest(path):
  h = hashlib.sha256()
  for full in discovery.walk_files(path):
    h.update(os.path.basename(full).encode())
    with open(full, "rb") as f:
      h.update(f.read())
  return h.hexdigest()


def layer_bytes(root):
  """Chunk/info objects under a layer dir (provenance excluded: it embeds
  wall-clock dates by design; in-flight .tmp.* atomic-write files too)."""
  out = {}
  for full in discovery.walk_files(root):
    if ".tmp." in os.path.basename(full):
      continue
    rel = os.path.relpath(full, root)
    if rel.startswith("provenance"):
      continue
    with open(full, "rb") as f:
      out[rel] = f.read()
  return out


def drain(queue):
  def stop(executed, empty):
    return empty and queue.enqueued == 0

  return queue.poll(lease_seconds=30, stop_fn=stop, verbose=False,
                    max_backoff_window=0.2)


def act_scale(workdir, runner):
  """100k-task enqueue rate + O(shards) status reads."""
  payload = serialize(PrintTask("scale"))

  base_q = FileQueue(f"fq://{workdir}/baseline")
  t0 = time.monotonic()
  base_q.insert(payload for _ in range(BASELINE_TASKS))
  base_rate = BASELINE_TASKS / max(time.monotonic() - t0, 1e-9)

  qspec = f"fq://{workdir}/scale"
  t0 = time.monotonic()
  TaskQueue(qspec).insert_batch(
    (payload for _ in range(SCALE_TASKS)), total=SCALE_TASKS,
  )
  batch_rate = SCALE_TASKS / max(time.monotonic() - t0, 1e-9)

  speedup = batch_rate / max(base_rate, 1e-9)
  gate("enqueue_rate",
       batch_rate >= ENQUEUE_RATE_GATE and speedup >= SPEEDUP_GATE,
       batch_tasks_per_sec=round(batch_rate),
       classic_tasks_per_sec=round(base_rate),
       speedup=round(speedup, 1),
       gates={"abs": ENQUEUE_RATE_GATE, "speedup": SPEEDUP_GATE})

  q = TaskQueue(qspec)
  t0 = time.monotonic()
  res = runner.invoke(cli_main, ["queue", "status", qspec])
  status_wall = time.monotonic() - t0
  gate("status_o_shards",
       res.exit_code == 0
       and status_wall <= STATUS_WALL_SEC
       and q.queue_files <= QUEUE_FILES_CAP
       and q.enqueued == SCALE_TASKS
       and f"enqueued: {SCALE_TASKS}" in res.output,
       exit_code=res.exit_code, wall_sec=round(status_wall, 3),
       queue_files=q.queue_files, tasks=SCALE_TASKS)
  if res.exit_code != 0:
    print(res.output[-2000:])


def act_chaos(workdir, runner):
  """Classic vs range-leased campaign under a stale-lease storm +
  preempt-style drain: byte-identical output, exact completions tally."""
  rng = np.random.default_rng(SEED)
  img = rng.integers(0, 255, (160, 160, 64)).astype(np.uint8)

  def make_tasks(layer):
    # fans out to an 18-task grid at this memory target
    return list(tc.create_downsampling_tasks(
      layer, mip=0, num_mips=1, memory_target=int(6e5), compress="gzip",
    ))

  # clean reference: classic one-file-per-task layout, solo leases
  classic_dir = os.path.join(workdir, "classic")
  classic_layer = f"file://{classic_dir}/layer"
  Volume.from_numpy(img, classic_layer, chunk_size=(32, 32, 32),
                    compress="gzip")
  cq = FileQueue(f"fq://{classic_dir}/q", max_deliveries=25)
  n_tasks = cq.insert(make_tasks(classic_layer))
  drain(cq)
  assert cq.is_empty() and cq.dlq_count == 0
  clean = layer_bytes(os.path.join(classic_dir, "layer"))

  # range-leased run, stormed
  range_dir = os.path.join(workdir, "ranged")
  range_layer = f"file://{range_dir}/layer"
  Volume.from_numpy(img, range_layer, chunk_size=(32, 32, 32),
                    compress="gzip")
  rq = FileQueue(f"fq://{range_dir}/q", max_deliveries=25)
  assert rq.insert_batch(make_tasks(range_layer)) == n_tasks

  # stale-lease storm: a worker leases a range, does SOME of the work,
  # then stalls past its lease — every late ack must be fenced
  doomed = rq.lease_batch(seconds=0.2, max_tasks=6)
  for task, _tok in doomed[:2]:
    task.execute()     # work done but never acked: at-least-once re-runs it
  time.sleep(0.3)
  fenced = rq.ack_batch([tok for _t, tok in doomed])
  gate("stale_lease_storm", len(doomed) == 6 and not any(fenced),
       leased=len(doomed), fenced_acks=sum(not ok for ok in fenced))

  # preempt-style drain mid-range: one member completes, one fails and
  # is requeued solo, the rest release back to the pool undelivered
  got = rq.lease_batch(seconds=60, max_tasks=6)
  task, tok = got[0]
  task.execute()
  acked = rq.delete(tok)
  rq.nack(got[1][1], "chaos: injected mid-range failure", requeue=True)
  for _t, tok in got[2:]:
    rq.release(tok)
  # the manipulated range is fully relinquished; the only lease left in
  # the dir is the expired storm lease awaiting recycle
  gate("preempt_drain",
       acked and len(got[0][1].parent) == 0 and rq.leased == len(doomed),
       acked=acked, range_left=len(got[0][1].parent),
       awaiting_recycle=rq.leased)

  # drain the survivors through the real batched worker loop
  res = runner.invoke(cli_main, [
    "execute", f"fq://{range_dir}/q", "-x", "--quiet",
    "--batch", str(SIM_BATCH),
  ])
  stormed = layer_bytes(os.path.join(range_dir, "layer"))
  gate("chaos_byte_identity",
       res.exit_code == 0 and stormed == clean,
       exit_code=res.exit_code, tasks=n_tasks,
       files=len(stormed), mismatched=sorted(
         k for k in set(clean) | set(stormed)
         if clean.get(k) != stormed.get(k))[:5])
  gate("completions_exact",
       rq.is_empty() and rq.completed == n_tasks and rq.dlq_count == 0,
       completed=rq.completed, tasks=n_tasks, dlq=rq.dlq_count)
  if res.exit_code != 0:
    print(res.output[-2000:])


def act_sim(workdir, runner):
  """Range-lease journal mines range_sizes; range-mode simulation lands
  within the sim-smoke tolerance of the measured wall-clock."""
  qpath = os.path.join(workdir, "simcampaign")
  qspec = f"fq://{qpath}"
  TaskQueue(qspec).insert_batch(
    [SleepTask(seconds=SIM_SLEEP_SEC) for _ in range(SIM_TASKS)],
  )
  t0 = time.monotonic()
  res = runner.invoke(cli_main, [
    "execute", qspec, "-x", "--quiet", "--batch", str(SIM_BATCH),
  ])
  actual_sec = time.monotonic() - t0
  if res.exit_code != 0:
    print(res.output[-2000:])
    gate("range_campaign", False, exit_code=res.exit_code)
    return

  model = replay.mine_journal(f"file://{qpath}/journal")
  gate("range_mining",
       model.total_tasks() >= SIM_TASKS
       and len(model.range_sizes) > 0
       and max(model.range_sizes) >= 2,
       tasks_mined=model.total_tasks(),
       range_rounds=len(model.range_sizes),
       sizes=sorted(set(model.range_sizes)))
  # range_sizes survive the model's serialization round-trip
  rt = replay.WorkloadModel.from_dict(
    json.loads(json.dumps(model.to_dict()))
  )
  gate("model_roundtrip", rt.range_sizes == model.range_sizes,
       n=len(rt.range_sizes))

  def run_sim(outdir):
    cfg = sim.SimConfig(
      workers=1, seed=SEED, batch_size=SIM_BATCH, poll_sec=0.5,
      range_lease=1,
    )
    s = sim.FleetSimulator(model, cfg)
    results = s.run()
    s.write_journal(f"file://{outdir}")
    return results

  sim_a = os.path.join(workdir, "sim_a")
  sim_b = os.path.join(workdir, "sim_b")
  ra = run_sim(sim_a)
  rb = run_sim(sim_b)
  err = abs(ra["makespan_sec"] - actual_sec) / actual_sec
  gate("range_sim_prediction", err <= TOLERANCE,
       predicted_sec=ra["makespan_sec"], actual_sec=round(actual_sec, 3),
       relative_error=round(err, 4), tolerance=TOLERANCE,
       range_rounds=ra["range_rounds"])
  gate("range_sim_determinism",
       ra == rb and ra["range_rounds"] > 0
       and journal_digest(sim_a) == journal_digest(sim_b),
       digest=journal_digest(sim_a)[:16])
  report["forecast"] = ra


def main():
  workdir = tempfile.mkdtemp(prefix="queue_smoke_")
  runner = CliRunner()
  try:
    act_scale(workdir, runner)
    act_chaos(workdir, runner)
    act_sim(workdir, runner)
  finally:
    report["ok"] = not failures
    with open("queue-report.json", "w") as f:
      json.dump(report, f, indent=2)
    shutil.rmtree(workdir, ignore_errors=True)
  if failures:
    print(f"[queue_smoke] FAILED gates: {failures}")
    return 1
  print("[queue_smoke] all gates passed")
  return 0


if __name__ == "__main__":
  sys.exit(main())
