"""512^3 skeleton-forge soak — the EXACT fixture generator, committed.

Round-4's "512^3 / 64-blob-label soak (40.5M fg)" generator was ad-hoc
and lost with the session; round 5's rebuild of "the same" fixture got
73.9M fg voxels of heavily OVERLAPPING blobs (multi-million-voxel merged
complexes) and measured 3124.7 s — a qualitatively harder workload, not
a regression signal (BASELINE.md round-5 section). This committed
generator is the canonical soak from round 5 on: grid-placed,
non-overlapping blobs (stable cost, ~31M fg), rng-seeded, printed fg
count — rounds compare on the fg rate (kvox-fg/s) it reports.

Run: PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python tools/skel_soak.py
"""

from __future__ import annotations

import os
import time

import numpy as np


def build_fixture(n=512, seed=0):
  """4x4x4 grid of 64 blobs, one per 128^3 cell, radius jittered within
  the cell so blobs never overlap or touch task borders."""
  rng = np.random.default_rng(seed)
  g = np.indices((n, n, n)).astype(np.float32)
  seg = np.zeros((n, n, n), dtype=np.uint64)
  lab = 1
  for cx in range(4):
    for cy in range(4):
      for cz in range(4):
        c = np.array([cx, cy, cz]) * 128 + 64 + rng.integers(-8, 9, 3)
        r = int(rng.integers(n // 12, n // 11))  # 42..46 vox
        m = ((g[0] - c[0]) ** 2 + (g[1] - c[1]) ** 2
             + (g[2] - c[2]) ** 2) < r * r
        seg[m] = lab
        lab += 1
  return seg


def main():
  from igneous_tpu import task_creation as tc
  from igneous_tpu.storage import clear_memory_storage
  from igneous_tpu.volume import Volume

  seg = build_fixture()
  fg = int((seg != 0).sum())
  print(f"fg: {fg}", flush=True)
  clear_memory_storage()
  Volume.from_numpy(
    seg, "mem://soak/skel", resolution=(16, 16, 40),
    chunk_size=(128, 128, 128), layer_type="segmentation",
  )
  tasks = list(tc.create_skeletonizing_tasks(
    "mem://soak/skel", shape=(256, 256, 256), dust_threshold=50,
    teasar_params={"scale": 4, "const": 200},
  ))
  print(f"tasks: {len(tasks)}", flush=True)
  t0 = time.time()
  for t in tasks:
    t.execute()
  dt = time.time() - t0
  print(f"SOAK wall: {dt:.1f}s  fg-rate: {fg / dt / 1e3:.1f} kvox-fg/s  "
        f"load={os.getloadavg()}")


if __name__ == "__main__":
  main()
