"""Serving-tier smoke (ISSUE 9 CI step).

Boots `igneous serve` as a real subprocess over a seeded file:// layer,
then asserts the acceptance criteria end to end:

  * a 16-client thundering herd on ONE cold chunk coalesces into
    exactly 1 backend fetch (serve.fetch == 1, serve.requests == 16 in
    the journaled counters);
  * served bytes are identical to direct storage reads, both in the
    compressed domain (Accept-Encoding: gzip -> stored wire bytes
    verbatim) and transcoded (no Accept-Encoding -> CloudFiles.get);
  * per-tier cache counters and per-request serve.request spans land in
    the journal (igneous fleet trace can render a request);
  * SIGTERM drains gracefully — an idle keep-alive connection does not
    wedge the drain and the process exits 0.

Usage: python tools/serve_smoke.py [--size 64]
"""

import argparse
import gzip
import http.client
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

HERD = 16


def serve_env():
  env = dict(os.environ)
  env.update({
    "JAX_PLATFORMS": "cpu",
    "PALLAS_AXON_POOL_IPS": "",
    "PYTHONUNBUFFERED": "1",
  })
  env.pop("AXON_POOL_SVC_OVERRIDE", None)
  env.pop("AXON_LOOPBACK_RELAY", None)
  return env


def get(port, path, headers=None):
  conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
  try:
    conn.request("GET", path, headers=headers or {})
    resp = conn.getresponse()
    return resp.status, dict(resp.getheaders()), resp.read()
  finally:
    conn.close()


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument("--size", type=int, default=64)
  args = ap.parse_args()

  tmp = tempfile.mkdtemp(prefix="igneous-serve-smoke-")
  path = f"file://{tmp}/layer"
  jpath = f"file://{tmp}/journal"

  from igneous_tpu.storage import CloudFiles
  from igneous_tpu.volume import Volume

  rng = np.random.default_rng(9)
  n = args.size
  data = rng.integers(0, 255, (n, n, n)).astype(np.uint8)
  Volume.from_numpy(data, path, chunk_size=(n, n, n))  # gzip-stored
  chunk = f"1_1_1/0-{n}_0-{n}_0-{n}"
  cf = CloudFiles(path)
  stored, method = cf.get_stored(chunk)
  assert method == "gzip", f"seed layer should be gzip-stored, got {method}"

  proc = subprocess.Popen(
    [sys.executable, "-m", "igneous_tpu", "serve", path,
     "--port", "0", "--host", "127.0.0.1", "--journal", jpath,
     "--no-synth"],
    env=serve_env(), cwd=REPO, stdout=subprocess.PIPE,
    stderr=subprocess.STDOUT, text=True,
  )
  try:
    port = None
    deadline = time.time() + 120
    for line in proc.stdout:
      sys.stdout.write(line)
      if line.startswith("{"):
        try:
          rec = json.loads(line)
        except ValueError:
          continue
        if rec.get("event") == "serve.listening":
          port = rec["port"]
          break
      if time.time() > deadline:
        break
    assert port, "serve never printed its listening line"

    # thundering herd FIRST (server fully cold): 16 concurrent clients,
    # one chunk — the coalescer must make exactly one origin fetch
    barrier = threading.Barrier(HERD)
    bodies = [None] * HERD

    def hammer(i):
      barrier.wait()
      _, _, bodies[i] = get(port, f"/{chunk}", {"Accept-Encoding": "gzip"})

    threads = [
      threading.Thread(target=hammer, args=(i,)) for i in range(HERD)
    ]
    for t in threads:
      t.start()
    for t in threads:
      t.join()
    assert all(b == stored for b in bodies), (
      "herd responses differ from the stored wire bytes"
    )
    print(f"herd: {HERD} clients, all byte-identical to storage")

    # byte identity, transcoded path (client accepts no gzip)
    status, headers, body = get(port, f"/{chunk}")
    assert status == 200 and "Content-Encoding" not in headers
    assert body == cf.get(chunk), "transcoded body != CloudFiles.get"
    assert gzip.decompress(stored) == body

    # warm hit off the RAM tier
    status, headers, _ = get(port, f"/{chunk}", {"Accept-Encoding": "gzip"})
    assert headers.get("X-Igneous-Cache") == "ram", headers.get(
      "X-Igneous-Cache"
    )

    # SIGTERM drain: an idle keep-alive connection must not wedge it
    idle = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    idle.request("GET", "/healthz")
    idle.getresponse().read()  # keep-alive: connection stays open, idle
    proc.send_signal(signal.SIGTERM)
    rc = proc.wait(timeout=60)
    idle.close()
    assert rc == 0, f"serve exited {rc} on SIGTERM (want clean drain = 0)"
    print("SIGTERM drain: exit 0 with an idle keep-alive connection open")
  finally:
    if proc.poll() is None:
      proc.kill()
      proc.wait(timeout=30)

  from igneous_tpu.observability import fleet
  from igneous_tpu.observability import journal as journal_mod

  records = list(journal_mod.read_records(jpath))
  assert records, "serve left no journal segments"
  counters = {}
  for rec in records:
    if rec.get("kind") == "counters":
      counters.update(rec.get("counters") or {})
  assert counters.get("serve.fetch") == 1, (
    f"herd of {HERD} must cost exactly 1 backend fetch, "
    f"saw {counters.get('serve.fetch')}"
  )
  assert counters.get("serve.requests", 0) >= HERD + 2
  leaders = counters.get("serve.coalesce.leaders", 0)
  waiters = counters.get("serve.coalesce.waiters", 0)
  ram_hits = counters.get("serve.cache.ram.hits", 0)
  assert leaders == 1, f"exactly one coalition leader expected, got {leaders}"
  assert waiters + ram_hits >= HERD - 1, (
    f"non-leader herd clients must ride the single flight or the RAM "
    f"tier: waiters={waiters} ram_hits={ram_hits}"
  )
  print(f"counters: fetch=1 leaders=1 waiters={waiters} ram_hits={ram_hits}")

  spans = [r for r in records if r.get("kind") == "span"]
  reqs = [s for s in spans if s.get("name") == "serve.request"]
  assert len(reqs) >= HERD, f"per-request spans missing ({len(reqs)})"
  sample = next(s for s in reqs if s.get("tier") == "origin")
  tree = fleet.trace_records(records, sample["trace"])
  assert any(s["name"] == "serve.fetch" for s in tree), (
    "origin request trace lacks its serve.fetch child span"
  )
  rendered = fleet.render_trace(tree)
  assert rendered
  print("\n".join(rendered))
  print("serve smoke OK")


if __name__ == "__main__":
  main()
