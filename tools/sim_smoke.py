#!/usr/bin/env python
"""Fleet-simulator smoke: mine a real campaign, predict it, close the loop.

CI acceptance for ISSUE 13, in four acts:

1. run a seeded, journaled SleepTask campaign against a real fq:// queue
   and measure its wall-clock;
2. mine the journal into a WorkloadModel, simulate the same campaign,
   and assert the predicted completion time lands within +/-20% of the
   measured one — and that two same-seed simulations are bit-identical
   (results AND emitted journal bytes);
3. run `igneous fleet status` against the *simulated* journal and
   require exit 0 (simulated output is first-class journal format);
4. inject a backlog and let `igneous fleet autoscale` (local subprocess
   actuator, scale-to-zero floor) scale a real worker pool up and back
   down, asserted via the autoscale.* counters the controller journals.

Writes sim-report.json next to the CWD for the CI artifact upload.
Exit 0 = all gates passed.
"""

import hashlib
import json
import os
import shutil
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from click.testing import CliRunner  # noqa: E402

from igneous_tpu.analysis import discovery  # noqa: E402
from igneous_tpu.cli import main as cli_main  # noqa: E402
from igneous_tpu.observability import fleet, replay, sim  # noqa: E402
from igneous_tpu.queues import TaskQueue  # noqa: E402
from igneous_tpu.tasks import SleepTask  # noqa: E402

TASKS = 48
SLEEP_SEC = 0.02
BATCH = 4
SEED = 1234
TOLERANCE = 0.20

report = {"gates": {}, "ok": False}
failures = []


def gate(name, ok, **detail):
  report["gates"][name] = {"ok": bool(ok), **detail}
  status = "PASS" if ok else "FAIL"
  print(f"[sim_smoke] {status} {name}: {detail}")
  if not ok:
    failures.append(name)


def journal_digest(path):
  h = hashlib.sha256()
  for full in discovery.walk_files(path):
    h.update(os.path.basename(full).encode())
    with open(full, "rb") as f:
      h.update(f.read())
  return h.hexdigest()


def main():
  workdir = tempfile.mkdtemp(prefix="sim_smoke_")
  runner = CliRunner()
  try:
    # -- act 1: the real campaign ------------------------------------------
    qpath = os.path.join(workdir, "campaign")
    qspec = f"fq://{qpath}"
    TaskQueue(qspec).insert(
      [SleepTask(seconds=SLEEP_SEC) for _ in range(TASKS)]
    )
    t0 = time.monotonic()
    res = runner.invoke(cli_main, [
      "execute", qspec, "-x", "--quiet", "--batch", str(BATCH),
    ])
    actual_sec = time.monotonic() - t0
    gate("campaign", res.exit_code == 0,
         exit_code=res.exit_code, wall_sec=round(actual_sec, 3))
    if res.exit_code != 0:
      print(res.output[-2000:])
      raise SystemExit(1)

    # -- act 2: mine + predict + determinism --------------------------------
    jpath = f"file://{qpath}/journal"
    model = replay.mine_journal(jpath)
    gate("mining", model.total_tasks() >= TASKS,
         tasks_mined=model.total_tasks(),
         types=sorted(model.task_types))

    def run_sim(outdir):
      cfg = sim.SimConfig(
        workers=1, seed=SEED, batch_size=BATCH, poll_sec=0.5,
      )
      s = sim.FleetSimulator(model, cfg)
      results = s.run()
      s.write_journal(f"file://{outdir}")
      return results

    sim_a = os.path.join(workdir, "sim_a")
    sim_b = os.path.join(workdir, "sim_b")
    ra = run_sim(sim_a)
    rb = run_sim(sim_b)
    predicted = ra["makespan_sec"]
    err = abs(predicted - actual_sec) / actual_sec
    gate("prediction", err <= TOLERANCE,
         predicted_sec=predicted, actual_sec=round(actual_sec, 3),
         relative_error=round(err, 4), tolerance=TOLERANCE)
    gate("determinism",
         ra == rb and journal_digest(sim_a) == journal_digest(sim_b),
         digest=journal_digest(sim_a)[:16])
    report["forecast"] = ra

    # -- act 3: fleet status on the simulated journal ----------------------
    res = runner.invoke(cli_main, [
      "fleet", "status", "--journal", f"file://{sim_a}",
    ])
    gate("fleet_status_on_sim", res.exit_code == 0,
         exit_code=res.exit_code)
    if res.exit_code != 0:
      print(res.output[-2000:])

    # -- act 4: the real autoscale loop ------------------------------------
    qpath2 = os.path.join(workdir, "autoscale")
    qspec2 = f"fq://{qpath2}"
    TaskQueue(qspec2).insert(
      [SleepTask(seconds=SLEEP_SEC) for _ in range(90)]
    )
    res = runner.invoke(cli_main, [
      "fleet", "autoscale", "-q", qspec2,
      "--min-workers", "0", "--max-workers", "3",
      "--horizon-sec", "2", "--cooldown-sec", "0.5", "--interval", "1.5",
      "--worker-arg", "--quiet",
      "--no-validate", "--json", "--iterations", "40",
    ])
    drained = TaskQueue(qspec2).backlog == 0
    counters = {}
    for rec in fleet.load_effective(f"file://{qpath2}/journal"):
      if (
        rec.get("kind") == "counters"
        and str(rec.get("worker", "")).startswith("autoscale-")
      ):
        counters = rec.get("counters") or counters
    ups = counters.get("autoscale.scale_up", 0)
    downs = counters.get("autoscale.scale_down", 0)
    gate("autoscale_loop",
         res.exit_code == 0 and drained and ups >= 1 and downs >= 1,
         exit_code=res.exit_code, drained=drained,
         scale_up=ups, scale_down=downs)
    if res.exit_code != 0:
      print(res.output[-2000:])
    report["autoscale_counters"] = {
      k: v for k, v in counters.items() if k.startswith("autoscale.")
    }
  finally:
    report["ok"] = not failures
    with open("sim-report.json", "w") as f:
      json.dump(report, f, indent=2)
    shutil.rmtree(workdir, ignore_errors=True)
  if failures:
    print(f"[sim_smoke] FAILED gates: {failures}")
    return 1
  print("[sim_smoke] all gates passed")
  return 0


if __name__ == "__main__":
  sys.exit(main())
