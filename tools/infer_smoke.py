"""Inference subsystem smoke (ISSUE 10 CI step).

Runs a 2-task InferenceTask campaign through `igneous execute` twice on a
virtual 8-device CPU mesh — once strictly serial, once through the staged
pipeline — and asserts the acceptance criteria end to end:

  * both runs exit 0 and write the SAME output bytes (the inference
    byte-determinism contract: pipelined == serial, chunk for chunk);
  * device.execute spans for the inference kernel landed in the journal
    (the conv apply really ran through the batched device path);
  * the journal's device ledger shows nonzero busy time, and the
    fast-path tally counted the campaign's patches;
  * `igneous fleet devices` exits 0 and shows the busy column.

Usage: python tools/infer_smoke.py [--size 128]
"""

import argparse
import os
import subprocess
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from igneous_tpu.analysis import discovery  # noqa: E402


def worker_env(pipeline: str):
  env = dict(os.environ)
  env.update({
    "JAX_PLATFORMS": "cpu",
    "PALLAS_AXON_POOL_IPS": "",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    "IGNEOUS_PIPELINE": pipeline,
    "IGNEOUS_PIPELINE_THREADS": "1",
    "IGNEOUS_JOURNAL_FLUSH_SEC": "2",
  })
  env.pop("AXON_POOL_SVC_OVERRIDE", None)
  env.pop("AXON_LOOPBACK_RELAY", None)
  return env


def layer_bytes(root):
  out = {}
  for full in discovery.walk_files(root):
    fname = os.path.basename(full)
    if "provenance" in fname or ".tmp." in fname:
      continue
    with open(full, "rb") as f:
      out[os.path.relpath(full, root)] = f.read()
  return out


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument("--size", type=int, default=128)
  args = ap.parse_args()

  tmp = tempfile.mkdtemp(prefix="igneous-infer-smoke-")
  src = f"file://{tmp}/src"
  model_path = f"file://{tmp}/model"
  qdir = f"{tmp}/q"
  jpath = f"file://{qdir}/journal"

  from igneous_tpu import task_creation as tc
  from igneous_tpu.infer import ModelSpec, init_params, save_model
  from igneous_tpu.observability import fleet
  from igneous_tpu.observability import device as device_mod
  from igneous_tpu.queues import FileQueue
  from igneous_tpu.volume import Volume

  rng = np.random.default_rng(10)
  n = args.size
  data = rng.integers(0, 255, (n, n, 32)).astype(np.uint8)
  Volume.from_numpy(data, src, chunk_size=(32, 32, 32), layer_type="image")

  spec = ModelSpec(
    "convnet3d", in_channels=1, out_channels=2,
    patch_shape=(32, 32, 16), overlap=(8, 8, 4), hidden=(4,),
  )
  save_model(model_path, spec, init_params(spec, seed=3))

  # task shape = half the volume -> exactly a 2-task campaign
  task_shape = (n // 2, n, 32)
  runs = {}
  for mode, pipeline in (("serial", "off"), ("pipelined", "1")):
    dest = f"file://{tmp}/out_{mode}"
    tasks = list(tc.create_inference_tasks(
      src, dest, model_path, shape=task_shape, batch_size=4,
    ))
    assert len(tasks) == 2, f"want a 2-task campaign, got {len(tasks)}"
    qspec = f"fq://{qdir}_{mode}"
    FileQueue(qspec).insert(tasks)
    proc = subprocess.run(
      [sys.executable, "-m", "igneous_tpu", "execute", qspec,
       "--batch", "2", "--exit-on-empty", "-q", "--lease-sec", "120",
       "--journal", jpath],
      env=worker_env(pipeline), cwd=REPO, capture_output=True, text=True,
      timeout=600,
    )
    sys.stdout.write(proc.stdout)
    sys.stderr.write(proc.stderr)
    assert proc.returncode == 0, f"{mode} worker rc={proc.returncode}"
    runs[mode] = layer_bytes(f"{tmp}/out_{mode}")

  serial, pipelined = runs["serial"], runs["pipelined"]
  assert serial, "serial run produced no output objects"
  assert set(serial) == set(pipelined), (
    "pipelined run wrote a different object set"
  )
  diff = [k for k in serial if serial[k] != pipelined[k]]
  assert not diff, f"byte mismatch pipelined vs serial: {diff}"
  print(f"byte identity: {len(serial)} objects identical")

  records = fleet.load(jpath)
  spans = [r for r in records if r.get("kind") == "span"]
  execs = [
    s for s in spans
    if s.get("name") == "device.execute"
    and str(s.get("kernel", "")).startswith("infer.")
  ]
  assert execs, "no inference device.execute spans in the journal"

  ledgers = device_mod.device_ledgers(records)
  assert ledgers, "no device ledger records in the journal"
  ledger = next(iter(ledgers.values()))
  assert ledger["busy_s"] and ledger["busy_s"] > 0, (
    f"device busy time not recorded: {ledger}"
  )
  fastpath = ledger.get("fastpath") or {}
  assert fastpath.get("batched", 0) > 0, (
    f"fast-path tally missing inference patches: {fastpath}"
  )
  print(f"ledger: busy_s={ledger['busy_s']} fastpath={fastpath}")

  proc = subprocess.run(
    [sys.executable, "-m", "igneous_tpu", "fleet", "devices",
     "--journal", jpath],
    env=worker_env("1"), cwd=REPO, capture_output=True, text=True,
    timeout=120,
  )
  sys.stdout.write(proc.stdout)
  assert proc.returncode == 0, (
    f"igneous fleet devices exited {proc.returncode}: {proc.stderr}"
  )
  assert "busy_s" in proc.stdout
  print("INFER_SMOKE_OK")


if __name__ == "__main__":
  main()
