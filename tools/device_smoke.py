"""Device telemetry plane smoke (ISSUE 7 CI step).

Runs a real pipelined downsample workload through `igneous execute`
on a virtual 8-device CPU mesh (batched device dispatches via
IGNEOUS_POOL_HOST=0) with a pre-published profiler capture request,
then asserts the acceptance criteria end to end:

  * device.execute AND device.compile spans landed in the journal;
  * the journal carries a cumulative per-worker device ledger with a
    busy ratio and per-kernel vox/s;
  * igneous_device_recompiles_total counted distinct signatures only
    (recompiles <= distinct signatures, both >= 1);
  * `igneous fleet devices` exits 0 and prints the merged table;
  * the flags-file profiler trigger produced capture artifacts under
    <journal>/profiles/ (optionally copied out for the CI artifact).

Usage: python tools/device_smoke.py [--size 128] [--profile-out DIR]
"""

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def worker_env(tmp):
  env = dict(os.environ)
  env.update({
    "JAX_PLATFORMS": "cpu",
    "PALLAS_AXON_POOL_IPS": "",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    "IGNEOUS_POOL_HOST": "0",        # device pyramid, not native host pool
    "IGNEOUS_PIPELINE": "1",
    "IGNEOUS_PIPELINE_THREADS": "1",
    "IGNEOUS_JOURNAL_FLUSH_SEC": "2",
  })
  env.pop("AXON_POOL_SVC_OVERRIDE", None)
  env.pop("AXON_LOOPBACK_RELAY", None)
  return env


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument("--size", type=int, default=256)
  ap.add_argument("--profile-out", default=None,
                  help="Copy captured profile artifacts here (CI upload).")
  args = ap.parse_args()

  tmp = tempfile.mkdtemp(prefix="igneous-device-smoke-")
  path = f"file://{tmp}/img"
  qdir = f"{tmp}/q"
  qspec = f"fq://{qdir}"
  jpath = f"file://{qdir}/journal"

  from igneous_tpu import task_creation as tc
  from igneous_tpu.analysis import discovery
  from igneous_tpu.observability import device as device_mod
  from igneous_tpu.queues import FileQueue
  from igneous_tpu.volume import Volume

  rng = np.random.default_rng(7)
  n = args.size
  data = rng.integers(0, 255, (n, n, 64)).astype(np.uint8)
  Volume.from_numpy(data, path, chunk_size=(32, 32, 32),
                    layer_type="image")
  tasks = list(tc.create_downsampling_tasks(
    path, mip=0, num_mips=1, memory_target=2 * 1024 * 1024
  ))
  assert len(tasks) >= 4, f"want a few tasks, got {len(tasks)}"
  FileQueue(qspec).insert(tasks)

  # publish the capture trigger BEFORE the worker starts: its first
  # journal poll must pick it up (the PR 6 flags-file pattern)
  req = device_mod.write_profile_request(jpath, duration_sec=1.0)
  print(f"profile request {req['id']} published")

  proc = subprocess.run(
    [sys.executable, "-m", "igneous_tpu", "execute", qspec,
     "--batch", "4", "--exit-on-empty", "--min-sec", "10", "-q",
     "--lease-sec", "60"],
    env=worker_env(tmp), cwd=REPO, capture_output=True, text=True,
    timeout=600,
  )
  sys.stdout.write(proc.stdout)
  sys.stderr.write(proc.stderr)
  assert proc.returncode == 0, f"worker failed rc={proc.returncode}"

  from igneous_tpu.observability import fleet

  records = fleet.load(jpath)
  spans = [r for r in records if r.get("kind") == "span"]
  execs = [s for s in spans if s.get("name") == "device.execute"]
  compiles = [s for s in spans if s.get("name") == "device.compile"]
  assert execs, "no device.execute spans in the journal"
  assert compiles, "no device.compile spans in the journal"
  assert all(s.get("device") for s in execs), "spans lack device attr"

  ledgers = device_mod.device_ledgers(records)
  assert ledgers, "no device ledger records in the journal"
  ledger = next(iter(ledgers.values()))
  assert ledger["busy_ratio"] is not None and ledger["dispatches"] >= 1
  assert ledger["recompiles"] >= 1
  assert ledger["recompiles"] <= ledger["distinct_signatures"] + 0, (
    "recompiles must count distinct signatures only"
  )
  kernels = ledger["kernels"]
  assert any(k.get("vox_per_sec") for k in kernels.values()), (
    "per-kernel vox/s missing from the ledger"
  )
  print(f"ledger: busy_ratio={ledger['busy_ratio']} "
        f"dispatches={ledger['dispatches']} "
        f"recompiles={ledger['recompiles']} kernels={sorted(kernels)}")

  proc = subprocess.run(
    [sys.executable, "-m", "igneous_tpu", "fleet", "devices",
     "--journal", jpath],
    env=worker_env(tmp), cwd=REPO, capture_output=True, text=True,
    timeout=120,
  )
  sys.stdout.write(proc.stdout)
  assert proc.returncode == 0, (
    f"igneous fleet devices exited {proc.returncode}: {proc.stderr}"
  )
  assert "busy_s" in proc.stdout

  artifacts = device_mod.list_profiles(jpath)
  assert artifacts, "profiler trigger produced no artifacts"
  print(f"profile artifacts: {len(artifacts)}")
  if args.profile_out:
    os.makedirs(args.profile_out, exist_ok=True)
    src_root = os.path.join(qdir, "journal", "profiles")
    for full in discovery.walk_files(src_root):
      rel = os.path.relpath(full, src_root)
      dest = os.path.join(args.profile_out, rel)
      os.makedirs(os.path.dirname(dest), exist_ok=True)
      shutil.copyfile(full, dest)
    print(f"copied artifacts to {args.profile_out}")

  print("DEVICE_SMOKE_OK")


if __name__ == "__main__":
  main()
