"""E2e throughput vs worker-process count (VERDICT r4 item 8).

Measures `igneous-tpu -p W execute --batch K` against real fq:// queues
on shared file:// volumes (tmpfs) for the two production suites:

  img: u8 downsample grid (the codec-bound path from BASELINE weak #5)
  seg: u64 skeleton forge (TEASAR-bound)

Emits one JSON line per (suite, workers) plus a markdown table for
BASELINE.md. On a 1-core host the expected result is flat scaling with
bounded per-worker overhead — the datum of interest is that nothing
COLLAPSES under concurrent lease traffic; real scaling numbers need a
multi-core window (recorded as such in BASELINE.md).

Run: python tools/worker_scaling.py [workers ...]
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import time

import numpy as np

ROOT = "/dev/shm/ig_scaling"
ENV = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")


def build_img(path):
  from igneous_tpu.volume import Volume

  rng = np.random.default_rng(0)
  # big enough that per-worker interpreter+jax startup (~4 s) does not
  # dominate the wall measurement
  data = rng.integers(0, 255, (1024, 1024, 64)).astype(np.uint8)
  Volume.from_numpy(data, path, chunk_size=(64, 64, 64))
  return int(data.size)


def build_seg(path):
  from igneous_tpu.volume import Volume

  rng = np.random.default_rng(0)
  n = 256
  g = np.indices((n, n, n)).astype(np.float32)
  seg = np.zeros((n, n, n), dtype=np.uint64)
  for i in range(24):
    c = rng.integers(n // 8, n - n // 8, 3)
    r = rng.integers(n // 12, n // 5)
    m = ((g[0] - c[0]) ** 2 + (g[1] - c[1]) ** 2 + (g[2] - c[2]) ** 2) < r * r
    seg[m] = i + 1
  Volume.from_numpy(
    seg, path, chunk_size=(128, 128, 128), layer_type="segmentation",
    resolution=(16, 16, 40),
  )
  return int(seg.size)


def make_tasks(suite, path):
  from igneous_tpu import task_creation as tc

  if suite == "img":
    return list(tc.create_downsampling_tasks(
      path, mip=0, num_mips=2, compress=None, memory_target=int(64e6),
    ))
  return list(tc.create_skeletonizing_tasks(
    path, shape=(128, 128, 128), dust_threshold=50,
    teasar_params={"scale": 4, "const": 200},
  ))


def run_suite(suite, workers, batch):
  from igneous_tpu.queues import FileQueue

  base = f"{ROOT}/{suite}_w{workers}"
  shutil.rmtree(base, ignore_errors=True)
  os.makedirs(base)
  vol_path = f"file://{base}/vol"
  voxels = build_img(vol_path) if suite == "img" else build_seg(vol_path)
  tasks = make_tasks(suite, vol_path)
  q = FileQueue(f"fq://{base}/q")
  q.insert(tasks)
  t0 = time.time()
  proc = subprocess.run(
    [sys.executable, "-m", "igneous_tpu.cli", "-p", str(workers),
     "execute", f"fq://{base}/q", "-x", "-q", "--batch", str(batch)],
    env=ENV, capture_output=True, text=True, timeout=3600,
  )
  wall = time.time() - t0
  if proc.returncode != 0:
    raise RuntimeError(proc.stderr[-800:])
  if not q.is_empty():
    raise RuntimeError(f"queue not drained: {suite} w={workers}")
  return {
    "suite": suite, "workers": workers, "batch": batch,
    "tasks": len(tasks), "wall_s": round(wall, 1),
    "voxps": round(voxels / wall, 1),
  }


def main():
  worker_counts = [int(v) for v in sys.argv[1:]] or [1, 2]
  rows = []
  for suite in ("img", "seg"):
    for w in worker_counts:
      r = run_suite(suite, w, batch=4)
      rows.append(r)
      print(json.dumps(r), flush=True)
  print("\n| suite | workers | wall s | vox/s |")
  print("|---|---|---|---|")
  for r in rows:
    print(f"| {r['suite']} | {r['workers']} | {r['wall_s']} "
          f"| {r['voxps']:,.0f} |")
  shutil.rmtree(ROOT, ignore_errors=True)


if __name__ == "__main__":
  main()
