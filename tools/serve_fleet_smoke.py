"""Serve-federation smoke (ISSUE 18 CI step).

Boots THREE real `igneous serve` replicas (subprocesses, auto-assigned
ports, shared file:// membership directory) over one seeded layer, then
proves the federation's headline economics end to end:

  * a seeded zipfian herd — the stationary request mix of a synthetic
    million-user viewer population — spread across all replicas costs
    EXACTLY one origin fetch per distinct chunk, fleet-wide
    (counter-asserted from the shared journal);
  * served bytes and ETags are identical on every replica, peer-filled
    or origin-filled;
  * the auto-assigned ports (serve + metrics) land machine-parsable in
    the `serve.listening` line, and the metrics port exposes the
    `igneous_serve_fleet_*` gauges;
  * SIGTERM-draining one replica leaves the fleet serving, including
    chunks the dead replica owned (graceful leave + origin fallback);
  * under forced overload (tiny `IGNEOUS_SERVE_QOS_RPS`) the fleet
    sheds with 503 + Retry-After instead of melting.

Writes the headline numbers to fleet-report.json (--report-out).

Usage: python tools/serve_fleet_smoke.py [--requests 600] [--clients 12]
"""

import argparse
import http.client
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

REPLICAS = 3


def serve_env(**extra):
  env = dict(os.environ)
  env.update({
    "JAX_PLATFORMS": "cpu",
    "PALLAS_AXON_POOL_IPS": "",
    "PYTHONUNBUFFERED": "1",
    # fast ring convergence + counters visible without waiting for drain
    "IGNEOUS_SERVE_FLEET_TTL_SEC": "3",
    "IGNEOUS_JOURNAL_FLUSH_SEC": "1",
  })
  env.pop("AXON_POOL_SVC_OVERRIDE", None)
  env.pop("AXON_LOOPBACK_RELAY", None)
  env.update(extra)
  return env


def get(port, path, headers=None):
  conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
  try:
    conn.request("GET", path, headers=headers or {})
    resp = conn.getresponse()
    return resp.status, dict(resp.getheaders()), resp.read()
  finally:
    conn.close()


def boot_replica(layer_path, jpath, members, extra_env=None):
  proc = subprocess.Popen(
    [sys.executable, "-m", "igneous_tpu", "serve", layer_path,
     "--port", "0", "--metrics-port", "0", "--host", "127.0.0.1",
     "--journal", jpath, "--no-synth"]
    + (["--peers-file", members] if members else []),
    env=serve_env(**(extra_env or {})), cwd=REPO, stdout=subprocess.PIPE,
    stderr=subprocess.STDOUT, text=True,
  )
  deadline = time.time() + 120
  listening = None
  for line in proc.stdout:
    sys.stdout.write(line)
    if line.startswith("{"):
      try:
        rec = json.loads(line)
      except ValueError:
        continue
      if rec.get("event") == "serve.listening":
        listening = rec
        break
    if time.time() > deadline:
      break
  assert listening, "replica never printed its serve.listening line"
  # satellite: --port 0 / --metrics-port 0 auto-assignment must land
  # every BOUND port in the machine-parsable readiness line
  assert listening["port"], listening
  assert listening["metrics_port"], listening
  # drain the rest of stdout on a reaper thread so the pipe never fills
  t = threading.Thread(
    target=lambda: [sys.stdout.write(ln) for ln in proc.stdout], daemon=True
  )
  t.start()
  return proc, listening


def aggregate_counters(jpath):
  """Latest counters snapshot per worker, summed across the fleet
  (each replica journals cumulative counters under its own worker id)."""
  from igneous_tpu.observability import journal as journal_mod

  latest = {}
  for rec in journal_mod.read_records(jpath):
    if rec.get("kind") != "counters":
      continue
    worker = rec.get("worker", "?")
    prev = latest.get(worker)
    if prev is None or rec.get("ts", 0) >= prev.get("ts", 0):
      latest[worker] = rec
  totals = {}
  for rec in latest.values():
    for k, v in (rec.get("counters") or {}).items():
      totals[k] = totals.get(k, 0) + v
  return totals


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument("--size", type=int, default=128, help="volume edge (vox)")
  ap.add_argument("--requests", type=int, default=600)
  ap.add_argument("--clients", type=int, default=12)
  ap.add_argument("--users", type=int, default=1_000_000,
                  help="synthetic viewer population behind the zipf mix")
  ap.add_argument("--seed", type=int, default=9)
  ap.add_argument("--report-out", default="fleet-report.json")
  args = ap.parse_args()

  tmp = tempfile.mkdtemp(prefix="igneous-fleet-smoke-")
  layer_path = f"file://{tmp}/layer"
  jpath = f"file://{tmp}/journal"
  members = f"file://{tmp}/members"

  from igneous_tpu.serve import HashRing, strong_etag
  from igneous_tpu.storage import CloudFiles

  from igneous_tpu.volume import Volume

  rng = np.random.default_rng(args.seed)
  n = args.size
  data = rng.integers(0, 255, (n, n, n)).astype(np.uint8)
  Volume.from_numpy(data, layer_path, chunk_size=(32, 32, 32))
  cf = CloudFiles(layer_path)
  chunks = sorted(k for k in cf.list() if k.startswith("1_1_1/"))
  assert len(chunks) >= 32, f"seed produced only {len(chunks)} chunks"
  # hold some chunks out of the herd so the drain phase can request
  # provably-cold keys owned by the dead replica
  herd_pool, reserved = chunks[:-8], chunks[-8:]

  report = {"requests": args.requests, "clients": args.clients,
            "users": args.users, "chunks": len(chunks)}
  procs = []
  try:
    infos = []
    for i in range(REPLICAS):
      proc, info = boot_replica(layer_path, jpath, members)
      procs.append(proc)
      infos.append(info)
    ports = [info["port"] for info in infos]
    urls = [info["self_url"] for info in infos]
    layer_name = "layer"

    # ring convergence: every replica must see all three members
    deadline = time.time() + 60
    while time.time() < deadline:
      rings = []
      for port in ports:
        _, _, body = get(port, "/-/fed/status")
        rings.append(json.loads(body)["ring"])
      if all(sorted(r) == sorted(urls) for r in rings):
        break
      time.sleep(0.25)
    else:
      raise AssertionError(f"ring never converged: {rings} != {urls}")
    print(f"ring converged: {len(urls)} replicas")

    # ---- phase 1: the zipfian million-user herd --------------------------
    # a zipf(s=1.1) popularity law over the chunk grid is the stationary
    # request mix of a large viewer population; seeded, so CI replays
    # the identical herd every run
    ranks = np.arange(1, len(herd_pool) + 1, dtype=np.float64)
    pop = 1.0 / ranks ** 1.1
    pop /= pop.sum()
    order = rng.permutation(len(herd_pool))  # popularity != grid order
    draws = rng.choice(len(herd_pool), size=args.requests, p=pop)
    requests = [herd_pool[order[d]] for d in draws]
    distinct = sorted(set(requests))

    per_client = [requests[i::args.clients] for i in range(args.clients)]
    statuses = []
    lock = threading.Lock()
    barrier = threading.Barrier(args.clients)

    def viewer(ci):
      got = []
      conns = {}
      barrier.wait()
      for j, key in enumerate(per_client[ci]):
        port = ports[(ci + j) % len(ports)]  # LB round-robin
        conn = conns.get(port)
        if conn is None:
          conn = conns[port] = http.client.HTTPConnection(
            "127.0.0.1", port, timeout=60
          )
        try:
          conn.request("GET", f"/{key}", headers={"Accept-Encoding": "gzip"})
          resp = conn.getresponse()
          resp.read()
          got.append(resp.status)
        except Exception:
          conns.pop(port).close()
          got.append(-1)
      for conn in conns.values():
        conn.close()
      with lock:
        statuses.extend(got)

    threads = [
      threading.Thread(target=viewer, args=(ci,))
      for ci in range(args.clients)
    ]
    t0 = time.perf_counter()
    for t in threads:
      t.start()
    for t in threads:
      t.join()
    herd_sec = time.perf_counter() - t0
    assert all(s == 200 for s in statuses), (
      f"non-200 in herd: {sorted(set(statuses))}"
    )
    rps = len(requests) / herd_sec
    print(f"herd: {len(requests)} requests ({len(distinct)} distinct chunks) "
          f"in {herd_sec:.2f}s = {rps:.0f} req/s")

    # ---- byte identity on every replica ----------------------------------
    for key in distinct[:8]:
      stored, _ = cf.get_stored(key)
      etag = strong_etag(stored)
      for port in ports:
        status, headers, body = get(
          port, f"/{key}", {"Accept-Encoding": "gzip"}
        )
        assert status == 200 and body == stored, (
          f"{key} differs on :{port}"
        )
        assert headers["ETag"] == etag
    print("byte identity: 8 chunks x 3 replicas, all == stored bytes")

    # ---- headline economics: 1 origin fetch per distinct cold chunk ------
    deadline = time.time() + 45
    totals = {}
    while time.time() < deadline:
      totals = aggregate_counters(jpath)
      if totals.get("serve.fetch", 0) >= len(distinct):
        break
      time.sleep(1.0)
    assert totals.get("serve.fetch", 0) == len(distinct), (
      f"fleet-wide origin fetches {totals.get('serve.fetch')} != "
      f"{len(distinct)} distinct cold chunks — federation leaked to origin"
    )
    peer_hits = totals.get("serve.peer.hits", 0)
    assert peer_hits > 0, "no peer fills at all — the ring never engaged"
    fills = peer_hits + totals.get("serve.fetch", 0)
    peer_hit_ratio = peer_hits / fills
    print(f"economics: origin fetches == {len(distinct)} distinct chunks, "
          f"peer fills {peer_hits} (peer-hit ratio {peer_hit_ratio:.2f})")

    # metrics port satellite: the fleet gauges are scrapeable
    _, _, mbody = get(infos[0]["metrics_port"], "/metrics")
    assert b"igneous_serve_fleet_peers_live" in mbody, (
      "metrics exposition lacks igneous_serve_fleet_peers_live"
    )

    # ---- drain one replica: the fleet keeps serving ----------------------
    ring = HashRing(urls)
    victim_idx = urls.index(ring.owner(layer_name, reserved[0]))
    victim = procs[victim_idx]
    victim.send_signal(signal.SIGTERM)
    rc = victim.wait(timeout=60)
    assert rc == 0, f"drained replica exited {rc} (want 0)"
    survivors = [p for i, p in enumerate(ports) if i != victim_idx]
    for key in reserved:  # includes chunks the dead replica owned
      stored, _ = cf.get_stored(key)
      status, _, body = get(
        survivors[0], f"/{key}", {"Accept-Encoding": "gzip"}
      )
      assert status == 200 and body == stored, (
        f"fleet lost {key} after draining one replica"
      )
    print("drain: SIGTERM'd the owner of reserved chunks, "
          "survivors still serve them byte-identically")

    report.update({
      "serve_fleet_req_per_sec": round(rps, 1),
      "distinct_chunks": len(distinct),
      "origin_fetches": totals.get("serve.fetch", 0),
      "peer_hits": peer_hits,
      "peer_hit_ratio": round(peer_hit_ratio, 4),
      "coalesce_leaders": totals.get("serve.coalesce.leaders", 0),
      "drained_replica": urls[victim_idx],
    })
  finally:
    for proc in procs:
      if proc.poll() is None:
        proc.send_signal(signal.SIGTERM)
    for proc in procs:
      if proc.poll() is None:
        try:
          proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
          proc.kill()

  # ---- phase 2: forced overload must shed, not melt ----------------------
  jpath_qos = f"file://{tmp}/journal-qos"
  proc, info = boot_replica(layer_path, jpath_qos, members=None, extra_env={
    "IGNEOUS_SERVE_QOS_RPS": "10",
    "IGNEOUS_SERVE_QOS_BURST_SEC": "1",
    "IGNEOUS_SERVE_QOS_WEIGHTS": "layer=1",
  })
  try:
    port = info["port"]
    status, _, _ = get(port, f"/{chunks[0]}")
    assert status == 200, "first request within burst must be admitted"
    sheds = 0
    retry_after = None
    for _ in range(80):
      status, headers, _ = get(port, f"/{chunks[0]}")
      if status == 503:
        sheds += 1
        retry_after = headers.get("Retry-After")
    assert sheds > 0, "forced overload (80 req @ 10 rps) never shed"
    assert retry_after and int(retry_after) >= 1, retry_after
    shed_rate = sheds / 81.0
    print(f"overload: {sheds}/81 shed with Retry-After={retry_after}s")
    proc.send_signal(signal.SIGTERM)
    rc = proc.wait(timeout=60)
    assert rc == 0, f"QoS replica exited {rc}"
  finally:
    if proc.poll() is None:
      proc.kill()
      proc.wait(timeout=30)

  qos_totals = aggregate_counters(jpath_qos)
  assert qos_totals.get("serve.shed.requests", 0) == sheds, (
    f"journaled sheds {qos_totals.get('serve.shed.requests')} != {sheds}"
  )
  report.update({
    "shed_rate_under_overload": round(shed_rate, 4),
    "sheds": sheds,
  })

  with open(args.report_out, "w") as f:
    json.dump(report, f, indent=2, sort_keys=True)
  print(f"report -> {args.report_out}")
  print("serve fleet smoke OK")


if __name__ == "__main__":
  main()
