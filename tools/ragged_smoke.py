"""Ragged paged batching acceptance smoke (ISSUE 12 CI step).

Runs a mixed-shape campaign on an 8-virtual-device CPU mesh and asserts
the paged-batching acceptance criteria end to end:

  * a whole-layer downsample whose grid has FOUR ragged edge cells of
    three distinct shapes: every edge cell rides the paged pyramid
    (``paged_cutouts``, zero solo ``edge_cutouts``), and the stored mips
    are byte-identical to the numpy oracle;
  * a mixed-shape paged CCL fleet byte-identical to solo
    ``connected_components`` on the device backend;
  * fast-path ratio >= 0.95 for the campaign (batched + paged
    deliveries over all deliveries);
  * EXACTLY ONE device.compile span per paged kernel in the journal —
    the one-signature-per-campaign contract;
  * the pad-waste gauge is populated (page slack is measured, not
    hidden).

Usage: python tools/ragged_smoke.py
"""

import os
import sys
import tempfile

# must precede the first jax import: the virtual mesh is a backend flag
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["IGNEOUS_TRACE_SAMPLE"] = "1"
os.environ["IGNEOUS_POOL_HOST"] = "0"       # device pyramid on CPU
os.environ["IGNEOUS_CCL_BACKEND"] = "device"
os.environ.pop("AXON_POOL_SVC_OVERRIDE", None)
os.environ.pop("AXON_LOOPBACK_RELAY", None)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np

PAGED_KERNEL_PREFIXES = ("pooling.paged_pyramid[", "ccl.paged[")


def check_ragged_downsample(rng, tmp):
  from igneous_tpu.ops import oracle
  from igneous_tpu.parallel import batched_downsample, make_mesh
  from igneous_tpu.volume import Volume

  # 641x385 grid at 256x256 cells: 2 full cells + 4 ragged edge cells of
  # 3 distinct shapes (129x256, 256x129, 129x129) — a genuinely
  # mixed-shape campaign for one paged pyramid
  data = rng.integers(0, 255, (641, 385, 64)).astype(np.uint8)
  path = f"file://{tmp}/img"
  Volume.from_numpy(data, path)
  stats = batched_downsample(
    path, num_mips=2, shape=(256, 256, 64), batch_size=8,
    mesh=make_mesh(8), compress=None,
  )
  assert stats["batched_cutouts"] == 2, stats
  assert stats["paged_cutouts"] == 4, stats
  assert stats["edge_cutouts"] == 0, stats
  vol = Volume(path)
  exp = oracle.np_downsample_with_averaging(data, (2, 2, 1), 2)
  for m in (1, 2):
    out = vol.download(vol.meta.bounds(m), mip=m)
    assert np.array_equal(out[..., 0], exp[m - 1]), f"mip {m} differs"
  print("paged downsample: 4 ragged edge cells paged, "
        "mips byte-identical to the oracle")


def check_ragged_ccl(rng):
  from igneous_tpu.ops.ccl import connected_components
  from igneous_tpu.parallel.paged import paged_ccl

  labs = [
    ((rng.random(s) < 0.55) * rng.integers(1, 4, s)).astype(np.uint32)
    for s in [(40, 33, 21), (17, 3, 9), (64, 64, 32)]
  ]
  got = paged_ccl(labs, 6)
  for lab, g in zip(labs, got):
    solo = connected_components(lab, 6)
    assert np.array_equal(g, solo), f"ccl {lab.shape} numbering differs"
  print("paged ccl: byte-identical to solo device CCL (3 ragged shapes)")


def main():
  tmp = tempfile.mkdtemp(prefix="igneous-ragged-smoke-")
  jpath = f"file://{tmp}/journal"

  import jax

  assert jax.device_count() == 8, (
    f"expected the 8-virtual-device mesh, got {jax.device_count()}"
  )

  from igneous_tpu.observability import device as device_mod
  from igneous_tpu.observability import fleet
  from igneous_tpu.observability.journal import Journal

  device_mod.install()
  journal = Journal(jpath, worker_id="ragged-smoke")

  rng = np.random.default_rng(12)
  check_ragged_downsample(rng, tmp)

  # the campaign's fast-path ratio: every delivery rode a batched or
  # paged dispatch, none fell to the solo host path
  fp = dict(device_mod.LEDGER.fastpath)
  total = fp.get("batched", 0) + fp.get("host", 0)
  assert total >= 6, fp
  ratio = fp.get("batched", 0) / total
  assert ratio >= 0.95, f"fastpath_ratio {ratio:.3f} < 0.95 ({fp})"
  print(f"fastpath_ratio {ratio:.3f} (batched {fp.get('batched', 0)} / "
        f"total {total})")

  check_ragged_ccl(rng)

  snap = device_mod.LEDGER.snapshot()
  assert snap["pad_bytes"] > 0, "pad-waste gauge never recorded"
  assert snap["pad_waste_ratio"] is not None
  print(f"pad_waste_ratio {snap['pad_waste_ratio']} "
        f"({snap['pad_bytes']} pad bytes over {snap['real_bytes']} real)")

  assert journal.flush(event="ragged-smoke"), "journal flush wrote nothing"

  records = fleet.load(jpath)
  spans = [r for r in records if r.get("kind") == "span"]
  compiles = {}
  for s in spans:
    if s.get("name") == "device.compile":
      k = s.get("kernel")
      compiles[k] = compiles.get(k, 0) + 1
  paged_kernels = sorted(
    k for k in compiles
    if any(k.startswith(p) for p in PAGED_KERNEL_PREFIXES)
  )
  assert paged_kernels, (
    f"no paged-kernel compile spans in the journal (saw {sorted(compiles)})"
  )
  for k in paged_kernels:
    assert compiles[k] == 1, (
      f"{k} compiled {compiles[k]} times — the whole ragged campaign "
      "must share ONE signature"
    )
  print(f"journal: one device.compile per paged kernel {paged_kernels}")
  print("RAGGED_SMOKE_OK")


if __name__ == "__main__":
  main()
