#!/usr/bin/env python
"""Chaos soak: a downsample pipeline under injected faults must produce
byte-identical output to a fault-free run (ISSUE 1 acceptance).

Two runs over the same synthetic volume:

  1. CLEAN  — ingest, create downsample tasks, drain an fq:// queue.
  2. CHAOS  — identical pipeline, but every storage backend is wrapped in
     igneous_tpu.chaos.ChaosStorage (transient failed puts, corrupted
     gets, 503 storms, a hard crash-between-compute-and-upload) and the
     queue in ChaosQueue (dropped lease deletes). Failed deliveries
     recycle on a short lease; transient faults heal after a bounded
     number of occurrences, so the queue drains.

The idempotency contract (tasks write deterministic bytes to disjoint
keys; gzip with mtime=0) makes the comparison exact: every chunk of the
chaos run must equal the clean run byte for byte. A third phase drops a
poison task into a --max-deliveries queue and asserts it lands in the
DLQ with its failure reason recoverable.

Usage:
  python tools/chaos_soak.py --seed 7 [--size 96] [--keep]

Exit code 0 = all assertions held. The seed names a deterministic fault
schedule — a failing seed reproduces exactly.
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from igneous_tpu import task_creation as tc  # noqa: E402
from igneous_tpu import telemetry  # noqa: E402
from igneous_tpu.chaos import ChaosConfig, ChaosQueue, chaos_storage  # noqa: E402
from igneous_tpu.queues import FileQueue  # noqa: E402
from igneous_tpu.tasks import FailTask  # noqa: E402
from igneous_tpu.volume import Volume  # noqa: E402


def make_tasks(path):
  # memory_target sized so the default 96^3 volume fans out to an 8-task
  # grid — the soak must exercise redelivery across MANY leases, not one
  return list(tc.create_downsampling_tasks(
    path, mip=0, num_mips=2, memory_target=int(6e5), compress="gzip",
  ))


def layer_bytes(root):
  """Every chunk/info object under a layer dir (provenance excluded: it
  embeds wall-clock dates by design)."""
  out = {}
  for dirpath, _dirs, files in os.walk(root):
    for fname in files:
      full = os.path.join(dirpath, fname)
      rel = os.path.relpath(full, root)
      if rel.startswith("provenance"):
        continue
      with open(full, "rb") as f:
        out[rel] = f.read()
  return out


def drain(queue, lease_seconds=0.5, deadline=120.0):
  """Poll until empty; chaos runs redeliver, so walls are bounded by the
  fault budget, not by optimism."""
  start = time.monotonic()

  def stop(executed, empty):
    if time.monotonic() - start > deadline:
      raise TimeoutError(
        f"soak queue failed to drain in {deadline}s "
        f"(enqueued={queue.enqueued}, counters={telemetry.counters_snapshot()})"
      )
    # "empty" only means nothing leasable right now; failed deliveries
    # are still out on expiring leases — wait for them to recycle
    return empty and queue.enqueued == 0

  return queue.poll(
    lease_seconds=lease_seconds, stop_fn=stop, verbose=False,
    max_backoff_window=0.2,
  )


def run_pipeline(workdir, img, chaos_cfg=None, tag=""):
  layer = f"file://{workdir}/layer"
  Volume.from_numpy(img, layer, chunk_size=(32, 32, 32), compress="gzip")
  tasks = make_tasks(layer)
  q = FileQueue(f"fq://{workdir}/q", max_deliveries=25)
  q.insert(tasks)
  if chaos_cfg is None:
    executed = drain(q)
  else:
    with chaos_storage(chaos_cfg):
      executed = drain(ChaosQueue(q, chaos_cfg), lease_seconds=0.5)
  assert q.is_empty(), f"{tag}: queue not drained"
  assert q.dlq_count == 0, f"{tag}: unexpected DLQ entries: {q.dlq_ls()}"
  return executed, layer_bytes(os.path.join(workdir, "layer"))


def poison_phase(workdir):
  """A task that raises on every delivery must end in the DLQ, reason
  recoverable — not in an infinite retry loop."""
  q = FileQueue(f"fq://{workdir}/poison", max_deliveries=3)
  q.insert(FailTask())
  for _ in range(4):
    q.poll(lease_seconds=0.01, stop_fn=lambda executed, empty: empty)
    time.sleep(0.03)
  q.lease(0.01)  # final recycle check promotes if a lease is still out
  assert q.dlq_count == 1, f"poison task not quarantined ({q.dlq_count})"
  rec = q.dlq_ls()[0]
  assert rec["deliveries"] == 3, rec
  assert any("intentional failure" in f["error"] for f in rec["failures"]), rec
  return rec


def main():
  ap = argparse.ArgumentParser(description=__doc__)
  ap.add_argument("--seed", type=int, default=0,
                  help="fault schedule seed (same seed = same storm)")
  ap.add_argument("--size", type=int, default=96,
                  help="cube edge of the synthetic volume")
  ap.add_argument("--keep", action="store_true",
                  help="keep the scratch dir for inspection")
  args = ap.parse_args()

  os.environ.setdefault("JAX_PLATFORMS", "cpu")
  scratch = tempfile.mkdtemp(prefix="chaos-soak-")
  telemetry.reset_counters()
  t0 = time.monotonic()
  try:
    rng = np.random.default_rng(args.seed)
    img = rng.integers(0, 255, (args.size, args.size, args.size // 2))
    img = img.astype(np.uint8)

    n_clean, clean = run_pipeline(
      os.path.join(scratch, "clean"), img, tag="clean"
    )

    cfg = ChaosConfig(
      seed=args.seed,
      put_fail=0.15,       # transient 503 on upload
      get_corrupt=0.10,    # bit-flipped download (gzip CRC catches it)
      storm=0.05,          # 503 on any op
      crash_put=0.10,      # worker dies between compute and upload
      drop_delete=0.20,    # completed task's ack lost -> duplicate run
      max_faults_per_key=2,
    )
    n_chaos, chaos = run_pipeline(
      os.path.join(scratch, "chaos"), img, chaos_cfg=cfg, tag="chaos"
    )

    missing = sorted(set(clean) - set(chaos))
    extra = sorted(set(chaos) - set(clean))
    assert not missing and not extra, (
      f"key sets differ: missing={missing[:5]} extra={extra[:5]}"
    )
    diff = [k for k in clean if clean[k] != chaos[k]]
    assert not diff, f"{len(diff)} objects differ byte-wise: {diff[:5]}"

    poison = poison_phase(scratch)

    counters = telemetry.counters_snapshot()
    injected = sum(v for k, v in counters.items() if k.startswith("chaos."))
    assert injected > 0, "chaos layer injected no faults — soak proved nothing"

    print(json.dumps({
      "seed": args.seed,
      "objects_compared": len(clean),
      "clean_executed": n_clean,
      "chaos_executed": n_chaos,
      "faults_injected": injected,
      "dlq_poison_deliveries": poison["deliveries"],
      "counters": counters,
      "wall_s": round(time.monotonic() - t0, 2),
      "byte_identical": True,
    }, indent=2))
  finally:
    if args.keep:
      print(f"scratch kept at {scratch}", file=sys.stderr)
    else:
      shutil.rmtree(scratch, ignore_errors=True)


if __name__ == "__main__":
  main()
