#!/usr/bin/env python
"""Chaos soak: a downsample pipeline under injected faults must produce
byte-identical output to a fault-free run (ISSUE 1 + ISSUE 2 acceptance).

``--scenario faults`` (default) — two runs over the same synthetic volume:

  1. CLEAN  — ingest, create downsample tasks, drain an fq:// queue.
  2. CHAOS  — identical pipeline, but every storage backend is wrapped in
     igneous_tpu.chaos.ChaosStorage (transient failed puts, corrupted
     gets, 503 storms, a hard crash-between-compute-and-upload) and the
     queue in ChaosQueue (dropped lease deletes, skewed lease clocks,
     stalled-then-resumed workers whose late acks must be fenced).
     Failed deliveries recycle on a short lease; transient faults heal
     after a bounded number of occurrences, so the queue drains.

``--scenario preemption`` — a worker-lifecycle storm (ISSUE 2): real
worker subprocesses drain the queue while the parent SIGTERMs one at a
seeded random point (it must drain gracefully: finish the in-flight
task, exit EXIT_PREEMPTED) and SIGKILLs another (its leases must recycle
to the survivors), plus one stalled-then-resumed zombie whose lease is
re-issued mid-stall and whose late delete must be fenced. The output
must be byte-identical to a clean run with ZERO duplicate completions in
the tally (completions == tasks exactly).

The idempotency contract (tasks write deterministic bytes to disjoint
keys; gzip with mtime=0) makes the comparison exact. The faults scenario
ends with a poison phase: a task that raises on every delivery must land
in the DLQ with its failure reason recoverable.

Usage:
  python tools/chaos_soak.py --seed 7 [--size 96] [--keep]
                             [--scenario faults|preemption|all]

Exit code 0 = all assertions held. The seed names a deterministic fault
schedule — a failing seed reproduces exactly.
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

import numpy as np  # noqa: E402

from igneous_tpu import task_creation as tc  # noqa: E402
from igneous_tpu.analysis import discovery, knobs  # noqa: E402
from igneous_tpu import telemetry  # noqa: E402
from igneous_tpu.chaos import ChaosConfig, ChaosQueue, chaos_storage  # noqa: E402
from igneous_tpu.queues import FileQueue  # noqa: E402
from igneous_tpu.tasks import FailTask  # noqa: E402
from igneous_tpu.volume import Volume  # noqa: E402


def make_tasks(path):
  # memory_target sized so the default 96^3 volume fans out to an 8-task
  # grid — the soak must exercise redelivery across MANY leases, not one
  return list(tc.create_downsampling_tasks(
    path, mip=0, num_mips=2, memory_target=int(6e5), compress="gzip",
  ))


def layer_bytes(root):
  """Every chunk/info object under a layer dir (provenance excluded: it
  embeds wall-clock dates by design; in-flight ``.tmp.*`` atomic-write
  files excluded too — a SIGKILLed worker can leave one behind, and
  readers never see them)."""
  out = {}
  for full in discovery.walk_files(root):
    if ".tmp." in os.path.basename(full):
      continue
    rel = os.path.relpath(full, root)
    if rel.startswith("provenance"):
      continue
    if rel.startswith("integrity"):
      # write-envelope sidecars (ISSUE 16): manifest segment names and
      # record timestamps are run-specific by design; chunk bytes are
      # the identity claim
      continue
    with open(full, "rb") as f:
      out[rel] = f.read()
  return out


def drain(queue, lease_seconds=0.5, deadline=120.0):
  """Poll until empty; chaos runs redeliver, so walls are bounded by the
  fault budget, not by optimism."""
  start = time.monotonic()

  def stop(executed, empty):
    if time.monotonic() - start > deadline:
      raise TimeoutError(
        f"soak queue failed to drain in {deadline}s "
        f"(enqueued={queue.enqueued}, counters={telemetry.counters_snapshot()})"
      )
    # "empty" only means nothing leasable right now; failed deliveries
    # are still out on expiring leases — wait for them to recycle
    return empty and queue.enqueued == 0

  return queue.poll(
    lease_seconds=lease_seconds, stop_fn=stop, verbose=False,
    max_backoff_window=0.2,
  )


import contextlib


@contextlib.contextmanager
def pipeline_disabled():
  """The CLEAN reference run always pins bytes with the strict-serial
  path, even when --pipeline turns the staged pipeline on for the
  fault/storm runs — that asymmetry IS the byte-identity claim."""
  prev = knobs.raw("IGNEOUS_PIPELINE")
  os.environ["IGNEOUS_PIPELINE"] = "off"
  try:
    yield
  finally:
    if prev is None:
      os.environ.pop("IGNEOUS_PIPELINE", None)
    else:
      os.environ["IGNEOUS_PIPELINE"] = prev


def run_pipeline(workdir, img, chaos_cfg=None, tag="", task_fn=None):
  layer = f"file://{workdir}/layer"
  Volume.from_numpy(img, layer, chunk_size=(32, 32, 32), compress="gzip")
  tasks = (task_fn or make_tasks)(layer)
  q = FileQueue(f"fq://{workdir}/q", max_deliveries=25)
  q.insert(tasks)
  if chaos_cfg is None:
    executed = drain(q)
  else:
    with chaos_storage(chaos_cfg):
      executed = drain(ChaosQueue(q, chaos_cfg), lease_seconds=0.5)
  assert q.is_empty(), f"{tag}: queue not drained"
  assert q.dlq_count == 0, f"{tag}: unexpected DLQ entries: {q.dlq_ls()}"
  return executed, layer_bytes(os.path.join(workdir, "layer"))


def poison_phase(workdir):
  """A task that raises on every delivery must end in the DLQ, reason
  recoverable — not in an infinite retry loop."""
  q = FileQueue(f"fq://{workdir}/poison", max_deliveries=3)
  q.insert(FailTask())
  for _ in range(4):
    q.poll(lease_seconds=0.01, stop_fn=lambda executed, empty: empty)
    time.sleep(0.03)
  q.lease(0.01)  # final recycle check promotes if a lease is still out
  assert q.dlq_count == 1, f"poison task not quarantined ({q.dlq_count})"
  rec = q.dlq_ls()[0]
  assert rec["deliveries"] == 3, rec
  assert any("intentional failure" in f["error"] for f in rec["failures"]), rec
  return rec


def run_faults_scenario(scratch, img, seed):
  """ISSUE 1 acceptance: fault storm vs clean run, byte-identical; then
  the poison task must end in the DLQ."""
  with pipeline_disabled():
    n_clean, clean = run_pipeline(
      os.path.join(scratch, "clean"), img, tag="clean"
    )

  cfg = ChaosConfig(
    seed=seed,
    put_fail=0.15,        # transient 503 on upload
    get_corrupt=0.10,     # bit-flipped download (gzip CRC catches it)
    storm=0.05,           # 503 on any op
    crash_put=0.10,       # worker dies between compute and upload
    drop_delete=0.20,     # completed task's ack lost -> duplicate run
    clock_skew=0.10,      # lease granted already-expired (skewed clock)
    stalled_worker=0.10,  # late ack after re-issue -> must be fenced
    max_faults_per_key=2,
  )
  n_chaos, chaos = run_pipeline(
    os.path.join(scratch, "chaos"), img, chaos_cfg=cfg, tag="chaos"
  )

  missing = sorted(set(clean) - set(chaos))
  extra = sorted(set(chaos) - set(clean))
  assert not missing and not extra, (
    f"key sets differ: missing={missing[:5]} extra={extra[:5]}"
  )
  diff = [k for k in clean if clean[k] != chaos[k]]
  assert not diff, f"{len(diff)} objects differ byte-wise: {diff[:5]}"

  poison = poison_phase(scratch)

  counters = telemetry.counters_snapshot()
  injected = sum(v for k, v in counters.items() if k.startswith("chaos."))
  assert injected > 0, "chaos layer injected no faults — soak proved nothing"

  return {
    "objects_compared": len(clean),
    "clean_executed": n_clean,
    "chaos_executed": n_chaos,
    "faults_injected": injected,
    "dlq_poison_deliveries": poison["deliveries"],
    "byte_identical": True,
  }


def run_corruption_scenario(scratch, img, seed):
  """ISSUE 16 acceptance: seeded torn writes + bit flips land silently
  mid-campaign (the producing tasks succeed; nothing reads the damage
  back during the run), then `igneous audit` must name EVERY injected
  fault — no more, no less — heal must converge, and the healed layer
  must be byte-identical to a clean run."""
  from igneous_tpu import integrity
  from igneous_tpu.queues import LocalTaskQueue
  from igneous_tpu.storage import COMPRESSION_EXTS
  from igneous_tpu.task_creation.audit import (
    create_integrity_audit_tasks,
    downsample_provenance,
    downsample_repair_tasks,
    load_findings,
  )
  from igneous_tpu.volume import Volume as Vol

  with pipeline_disabled():
    _, clean = run_pipeline(
      os.path.join(scratch, "cor-clean"), img, tag="cor-clean"
    )

  # Deterministic injection: the regex picks the x=0,y=0 column of output
  # chunks (only task outputs look like "<mip dir>/<bbox>"; queue/journal/
  # provenance writes don't match, and the mip-0 ingest runs before chaos
  # wraps the backends anyway). torn_write=0.5 + bit_flip=1.0 means every
  # matching put is corrupted — a seeded mix of the two modes — while
  # off-column chunks stay clean, so the exact-match assert below tests
  # both completeness (every fault found) AND precision (no false
  # positives on clean chunks). max_faults_per_key=1: each damaged key is
  # damaged exactly once, so `injected` is the exact ground truth.
  cfg = ChaosConfig(
    seed=seed,
    torn_write=0.5,
    bit_flip=1.0,
    corrupt_key_re=r"^\d+_\d+_\d+/0-\d+_0-\d+_",
    max_faults_per_key=1,
  )
  workdir = os.path.join(scratch, "cor-chaos")
  _, _ = run_pipeline(workdir, img, chaos_cfg=cfg, tag="cor-chaos")
  integrity.flush_all()

  assert cfg.injected, "corruption scenario injected nothing — re-seed"
  exts = tuple(e for e in COMPRESSION_EXTS.values() if e)
  injected_keys = set()
  for _op, key in cfg.injected:
    for ext in exts:
      if key.endswith(ext):
        key = key[: -len(ext)]
        break
    injected_keys.add(key)

  layer = f"file://{workdir}/layer"
  report_dir = f"{layer}/integrity/audit"
  prov = downsample_provenance(Vol(layer, mip=0))
  assert prov is not None, "downsample campaign left no provenance"
  mips = range(int(prov["mip"]) + 1, int(prov["mip"]) + int(prov["num_mips"]) + 1)

  def audit_round():
    for mip in mips:
      LocalTaskQueue(parallel=1, progress=False).insert(
        create_integrity_audit_tasks(layer, mip, report_dir)
      )
    return load_findings(report_dir)

  findings, totals = audit_round()
  detected = {f["key"] for f in findings}
  assert detected == injected_keys, (
    f"audit missed or invented faults: "
    f"missed={sorted(injected_keys - detected)[:5]} "
    f"extra={sorted(detected - injected_keys)[:5]}"
  )

  repairs, unhealable = downsample_repair_tasks(layer, findings)
  assert not unhealable, f"unhealable findings: {unhealable[:3]}"
  assert repairs, "findings produced no repair tasks"
  LocalTaskQueue(parallel=1, progress=False).insert(repairs)
  integrity.flush_all()  # repair puts must reach the manifests pre-re-audit

  refindings, _ = audit_round()
  assert not refindings, f"heal did not converge: {refindings[:3]}"

  chaos = layer_bytes(os.path.join(workdir, "layer"))
  missing = sorted(set(clean) - set(chaos))
  extra = sorted(set(chaos) - set(clean))
  assert not missing and not extra, (
    f"key sets differ after heal: missing={missing[:5]} extra={extra[:5]}"
  )
  diff = [k for k in clean if clean[k] != chaos[k]]
  assert not diff, f"{len(diff)} objects differ post-heal: {diff[:5]}"

  counters = telemetry.counters_snapshot()
  return {
    "objects_compared": len(clean),
    "faults_injected": len(cfg.injected),
    "torn_writes": counters.get("chaos.torn_write", 0),
    "bit_flips": counters.get("chaos.bit_flip", 0),
    "findings": len(findings),
    "repair_tasks": len(repairs),
    "audited_chunks": totals["chunks"],
    "healed_byte_identical": True,
  }


# one real worker process: graceful-drain wiring identical to `igneous
# execute` (StopFlag + signal handlers + heartbeats), plus a per-task
# delay so the storm reliably catches workers mid-run, and a ready-file
# touched once handlers are live (signals before that would just kill the
# interpreter mid-import, which is the SIGKILL case, not the drain case)
_STORM_WORKER_SRC = r"""
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import igneous_tpu.tasks  # register task classes
from igneous_tpu import lifecycle
from igneous_tpu.observability import journal as journal_mod
from igneous_tpu.queues import FileQueue

spec, lease_sec, task_delay, hb_sec, ready_path = (
  sys.argv[1], float(sys.argv[2]), float(sys.argv[3]), float(sys.argv[4]),
  sys.argv[5],
)
flag = lifecycle.StopFlag()
lifecycle.install_signal_handlers(flag)
q = FileQueue(spec)
# ISSUE 5 acceptance: each storm worker journals its spans; the SIGTERMed
# one's drain flush must leave its last batch behind for the merge
journal_mod.set_active(
  journal_mod.Journal(journal_mod.journal_path_for(q, spec))
)
with open(ready_path, "w") as f:
  f.write(str(os.getpid()))
q.poll(
  lease_seconds=lease_sec,
  verbose=False,
  stop_fn=lambda executed, empty: empty and q.enqueued == 0,
  max_backoff_window=0.2,
  before_fn=lambda task: time.sleep(task_delay),
  drain_flag=flag,
  heartbeat_seconds=hb_sec,
)
sys.exit(lifecycle.EXIT_PREEMPTED if flag.is_set() else 0)
"""


def run_preemption_storm(scratch, img, seed, trace_out=None):
  """ISSUE 2 acceptance: SIGTERM/SIGKILL workers at seeded random points
  plus one stalled-then-resumed zombie; output byte-identical to a clean
  run, zero duplicate task completions in the tally."""
  import random
  import signal
  import subprocess

  from igneous_tpu import lifecycle

  del img  # the storm needs a real task GRID, not --size's single cell:
  # a one-task queue makes kill timing meaningless. 160x160x64 fans out
  # to an 18-task grid at this memory target regardless of --size.
  rng_img = np.random.default_rng(seed)
  img = rng_img.integers(0, 255, (160, 160, 64)).astype(np.uint8)

  def storm_tasks(path):
    return list(tc.create_downsampling_tasks(
      path, mip=0, num_mips=1, memory_target=int(6e5), compress="gzip",
    ))

  with pipeline_disabled():
    n_clean, clean = run_pipeline(
      os.path.join(scratch, "storm-clean"), img, tag="storm-clean",
      task_fn=storm_tasks,
    )

  workdir = os.path.join(scratch, "storm")
  layer = f"file://{workdir}/layer"
  Volume.from_numpy(img, layer, chunk_size=(32, 32, 32), compress="gzip")
  tasks = storm_tasks(layer)
  spec = f"fq://{workdir}/q"
  q = FileQueue(spec)
  n_tasks = q.insert(tasks)
  assert n_tasks >= 8, f"storm needs a task grid, got {n_tasks}"

  # the stalled zombie: lease a task, DO the work, then stall past the
  # lease while the storm re-issues and completes it; the late ack at the
  # end must be fenced (this is what keeps the completions tally exact)
  zombie = q.lease(1.0)
  assert zombie is not None
  ztask, zlease = zombie
  ztask.execute()

  rng = random.Random(seed)
  env = dict(os.environ, JAX_PLATFORMS="cpu")
  env["PYTHONPATH"] = (
    REPO_ROOT + os.pathsep + env["PYTHONPATH"]
    if env.get("PYTHONPATH") else REPO_ROOT
  )
  ready = [os.path.join(workdir, f"ready-{i}") for i in range(3)]
  workers = [
    subprocess.Popen(
      [sys.executable, "-c", _STORM_WORKER_SRC,
       spec, "1.5", "0.25", "0.3", ready[i]],
      env=env,
    )
    for i in range(3)
  ]
  deadline = time.monotonic() + 180
  while time.monotonic() < deadline and not all(
    os.path.exists(r) for r in ready
  ):
    time.sleep(0.05)
  assert all(os.path.exists(r) for r in ready), "storm workers never started"

  # seeded random kill points, once the fleet is actually processing
  time.sleep(rng.uniform(0.2, 0.8))
  workers[0].send_signal(signal.SIGTERM)  # graceful drain expected
  time.sleep(rng.uniform(0.2, 0.8))
  if workers[1].poll() is None:
    workers[1].send_signal(signal.SIGKILL)  # hard death: leases recycle
  exit_codes = [w.wait(timeout=300) for w in workers]

  # a SIGTERM delivered to a live worker must drain, not fail (0 covers
  # the rare case it finished the queue before the signal landed)
  assert exit_codes[0] in (lifecycle.EXIT_PREEMPTED, 0), exit_codes
  assert exit_codes[1] in (-signal.SIGKILL, 0), exit_codes

  # backstop: recycle anything the SIGKILLed worker stranded and finish
  drain(q, lease_seconds=1.5, deadline=180.0)
  assert q.is_empty(), "storm queue not drained"

  # the zombie wakes: its lease expired and the task was re-issued and
  # completed by a live worker — the late delete must be rejected
  completed_before = q.completed
  assert q.delete(zlease) is False, "zombie delete was not fenced"
  assert q.completed == completed_before
  zombie_fences = telemetry.counters_snapshot().get("zombie.delete", 0)
  assert zombie_fences >= 1

  # ZERO duplicate completions: the tally counts each task exactly once,
  # despite kills, redeliveries, and the zombie
  assert q.completed == n_tasks, (
    f"duplicate/lost completions: tally={q.completed} tasks={n_tasks}"
  )

  storm = layer_bytes(os.path.join(workdir, "layer"))
  missing = sorted(set(clean) - set(storm))
  extra = sorted(set(storm) - set(clean))
  assert not missing and not extra, (
    f"key sets differ: missing={missing[:5]} extra={extra[:5]}"
  )
  diff = [k for k in clean if clean[k] != storm[k]]
  assert not diff, f"{len(diff)} objects differ byte-wise: {diff[:5]}"

  # ISSUE 5 acceptance: journal segments survive the preemption storm
  # (incl. the SIGTERMed worker's drain batch) and merge into one fleet
  # view with every executed task's span
  from igneous_tpu.observability import fleet, perfetto

  jpath = f"file://{workdir}/q/journal"
  records = fleet.load(jpath)
  assert records, "no journal segments survived the storm"
  journal_workers = {
    r.get("worker") for r in records if r.get("kind") == "span"
  }
  assert journal_workers, "journal has no span records"
  drain_batches = [
    r for r in records
    if r.get("kind") == "counters" and r.get("event") == "drain"
  ]
  # exit 83 means the SIGTERM landed mid-poll: its drain flush must have
  # left a final batch (exit 0 = queue drained first; no drain batch due)
  assert drain_batches or exit_codes[0] == 0, (
    "SIGTERMed worker exited 83 but left no drain journal batch"
  )
  merged = fleet.status(records)
  assert merged["tasks"] >= 1, merged
  if trace_out:
    n_events = perfetto.dump(records, trace_out)
    assert n_events > 0, "perfetto export produced no events"

  return {
    "tasks": n_tasks,
    "clean_executed": n_clean,
    "worker_exit_codes": exit_codes,
    "completions_tally": q.completed,
    "zombie_delete_fenced": zombie_fences,
    "objects_compared": len(clean),
    "byte_identical": True,
    "journal_segments": len({r.get("segment") for r in records}),
    "journal_workers": sorted(w for w in journal_workers if w),
    "journal_drain_batches": len(drain_batches),
    "fleet_tasks_merged": merged["tasks"],
  }


# a worker that executes a slice of the queue then exits, journaling
# aggressively — the HEALTHY half of the stall scenario's fleet
_STALL_WORKER_SRC = r"""
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["IGNEOUS_JOURNAL_FLUSH_SEC"] = "0.2"
import igneous_tpu.tasks  # register task classes
from igneous_tpu.observability import journal as journal_mod
from igneous_tpu.queues import FileQueue

spec, num_tasks, task_delay = sys.argv[1], int(sys.argv[2]), float(sys.argv[3])
q = FileQueue(spec)
journal_mod.set_active(
  journal_mod.Journal(journal_mod.journal_path_for(q, spec))
)
q.poll(
  lease_seconds=30,
  verbose=False,
  stop_fn=lambda executed, empty: empty or executed >= num_tasks,
  max_backoff_window=0.2,
  before_fn=lambda task: time.sleep(task_delay),
)
journal_mod.disarm_last_will()  # clean exit: drain batch, no stall flag
"""


def run_stall_health_scenario(scratch, seed, health_out=None):
  """ISSUE 6 acceptance: one injected stalled worker + a backlogged
  queue. ``igneous fleet check`` must exit non-zero NAMING the stalled
  worker, leave a ``health.straggler`` event in the journal, recommend
  desired_workers > current workers, and ``fleet status`` over compacted
  rollups must match the raw-segment view."""
  import subprocess

  from igneous_tpu.observability import fleet, journal as journal_mod, rollup
  from igneous_tpu.observability import trace

  rng_img = np.random.default_rng(seed)
  img = rng_img.integers(0, 255, (160, 160, 64)).astype(np.uint8)
  workdir = os.path.join(scratch, "stall")
  layer = f"file://{workdir}/layer"
  Volume.from_numpy(img, layer, chunk_size=(32, 32, 32), compress="gzip")
  tasks = list(tc.create_downsampling_tasks(
    layer, mip=0, num_mips=1, memory_target=int(6e5), compress="gzip",
  ))
  spec = f"fq://{workdir}/q"
  q = FileQueue(spec)
  n_tasks = q.insert(tasks)
  assert n_tasks >= 8, f"stall scenario needs a task grid, got {n_tasks}"
  jpath = journal_mod.journal_path_for(q, spec)

  # the INJECTED STALLED WORKER: leases a task, journals once (so the
  # health plane knows it exists), then goes silent holding the lease —
  # the exact shape of a wedged pod whose heartbeat thread died
  stalled_id = f"stalled-{os.getpid()}"
  zombie = q.lease(600)
  assert zombie is not None
  stalled_journal = journal_mod.Journal(jpath, worker_id=stalled_id)
  journal_mod.set_active(stalled_journal)
  trace.record_root("task", time.time() - 1.0, 0.9, worker=stalled_id)
  stalled_journal.flush(event="interval")
  journal_mod.set_active(None)
  stalled_at = time.monotonic()

  # the healthy worker drains HALF the queue then exits cleanly — the
  # check below must see throughput AND remaining backlog
  env = dict(os.environ, JAX_PLATFORMS="cpu")
  env["PYTHONPATH"] = (
    REPO_ROOT + os.pathsep + env["PYTHONPATH"]
    if env.get("PYTHONPATH") else REPO_ROOT
  )
  live = subprocess.run(
    [sys.executable, "-c", _STALL_WORKER_SRC,
     spec, str(max(n_tasks // 2, 2)), "0.3"],
    env=env, timeout=300,
  )
  assert live.returncode == 0, f"healthy worker failed: {live.returncode}"
  backlog = q.backlog
  assert backlog > 0, "stall scenario needs remaining backlog"

  # let the stalled worker age past the detector threshold
  stall_sec = 2.0
  time.sleep(max(0.0, stall_sec + 0.5 - (time.monotonic() - stalled_at)))

  report_path = health_out or os.path.join(scratch, "health-report.json")
  check = subprocess.run(
    [sys.executable, "-m", "igneous_tpu", "fleet", "check",
     "-q", spec, "--stall-sec", str(stall_sec), "--horizon-sec", "1",
     "--json", "--out", report_path],
    env=env, capture_output=True, text=True, timeout=120,
  )
  assert check.returncode == 2, (
    f"fleet check must exit 2 on a stalled worker, got {check.returncode}: "
    f"{check.stdout}\n{check.stderr}"
  )
  report = json.loads(check.stdout)
  flagged = {s["worker"] for s in report["stragglers"]}
  assert stalled_id in flagged, (stalled_id, report["stragglers"])
  auto = report["autoscale"]
  assert auto["desired_workers"] > auto["current_workers"], auto
  events = [
    r for r in fleet.load(jpath)
    if r.get("kind") == "span" and r.get("name") == "health.straggler"
  ]
  assert any(e.get("flagged") == stalled_id for e in events), events

  # rollup agreement: compacted view must match the raw-segment view
  st_raw = fleet.status(fleet.load(jpath))
  res = rollup.compact(jpath)
  assert res["segments_compacted"] > 0, res
  st_eff = fleet.status(fleet.load_effective(jpath))
  assert st_raw == st_eff, {
    k: (st_raw.get(k), st_eff.get(k))
    for k in set(st_raw) | set(st_eff) if st_raw.get(k) != st_eff.get(k)
  }

  return {
    "tasks": n_tasks,
    "backlog_at_check": backlog,
    "stalled_worker": stalled_id,
    "flagged": sorted(flagged),
    "desired_workers": auto["desired_workers"],
    "current_workers": auto["current_workers"],
    "health_report": report_path,
    "rollup_segments_compacted": res["segments_compacted"],
    "rollup_status_matches_raw": True,
  }


def run_hostile_scenario(scratch, seed):
  """ISSUE 17 acceptance: a continuous seeded kill/stall/preempt +
  speculation storm over a full range-lease campaign driven by the
  closed-loop campaign runner. The output must be byte-identical to a
  clean control, completions == tasks EXACTLY (first-ack-wins fencing,
  never double-counted), zero DLQ leakage, and the speculation ledger
  must reconcile from the journal alone: won + fenced == issued. The
  report also carries a `fleet simulate` forecast mined from the
  hostile journal itself — it must land within ±20% of the live run."""
  import random
  import signal

  from igneous_tpu.observability import (
    autoscale,
    campaign,
    fleet,
    health,
    journal as journal_mod,
    replay,
    sim as sim_mod,
  )

  rng_img = np.random.default_rng(seed)
  img = rng_img.integers(0, 255, (160, 160, 64)).astype(np.uint8)

  def hostile_tasks(path):
    return list(tc.create_downsampling_tasks(
      path, mip=0, num_mips=1, memory_target=int(6e5), compress="gzip",
    ))

  with pipeline_disabled():
    n_clean, clean = run_pipeline(
      os.path.join(scratch, "hostile-clean"), img, tag="hostile-clean",
      task_fn=hostile_tasks,
    )

  workdir = os.path.join(scratch, "hostile")
  layer = f"file://{workdir}/layer"
  Volume.from_numpy(img, layer, chunk_size=(32, 32, 32), compress="gzip")
  # the downsample grid carries the byte-identity claim; interleaved
  # SleepTasks (they write nothing) stretch the campaign across enough
  # driver ticks for the storm to land mid-range — without them the 18
  # real tasks drain in ~2s and every fault misses
  from igneous_tpu.tasks import SleepTask
  tasks = hostile_tasks(layer)
  tasks += [SleepTask(seconds=0.6) for _ in range(30)]
  spec = f"fq://{workdir}/q"

  # few, FAT segments: range leases must hold real unfinished tails for
  # speculation to twin and thieves to carve. Classic insert() writes
  # one file per task (no ranges at all) — the batched wire protocol
  # with a known total spreads the grid across IGNEOUS_QUEUE_SHARDS
  # segment files, and --batch workers lease them as ranges
  prev_shards = knobs.raw("IGNEOUS_QUEUE_SHARDS")
  os.environ["IGNEOUS_QUEUE_SHARDS"] = "3"
  try:
    q = FileQueue(spec, max_deliveries=25)
    n_tasks = q.insert_batch(tasks, total=len(tasks))
  finally:
    if prev_shards is None:
      os.environ.pop("IGNEOUS_QUEUE_SHARDS", None)
    else:
      os.environ["IGNEOUS_QUEUE_SHARDS"] = prev_shards
  assert n_tasks >= 8, f"hostile storm needs a task grid, got {n_tasks}"
  jpath = journal_mod.journal_path_for(q, spec)

  env = {
    "JAX_PLATFORMS": "cpu",
    "PYTHONPATH": (
      REPO_ROOT + os.pathsep + os.environ["PYTHONPATH"]
      if os.environ.get("PYTHONPATH") else REPO_ROOT
    ),
    # workers journal aggressively (stall detection reads flush age),
    # steal claims when idle, and speculation twins fresh leases too
    "IGNEOUS_JOURNAL_FLUSH_SEC": "0.2",
    "IGNEOUS_STEAL": "1",
    "IGNEOUS_STEAL_MIN_HELD_SEC": "1.0",
    "IGNEOUS_SPECULATE_MIN_HELD_SEC": "0",
  }
  os.environ["IGNEOUS_SPECULATE_MIN_HELD_SEC"] = "0"
  actuator = autoscale.LocalPoolActuator(
    spec,
    # --batch 4 engages the LeaseBatcher => fq segments arrive as RANGE
    # leases; --lease-sec 20 outlives the stall window so speculation
    # (not expiry recycling) is what rescues the frozen worker's tail
    worker_args=["--lease-sec", "20", "--batch", "4"],
    env=env, grace_sec=60.0,
  )
  policy = autoscale.AutoscalePolicy(
    min_workers=2, max_workers=3, horizon_sec=5.0,
    hysteresis=0.2, cooldown_sec=1.0, step_max=2,
  )
  runner = campaign.CampaignRunner(
    jpath, q, actuator,
    policy=policy,
    health_config=health.HealthConfig(stall_sec=3.0),
    tick_sec=1.0, speculate=True, max_wall_sec=240.0,
  )

  # the continuous storm, keyed to driver ticks: freeze one worker
  # mid-range (SIGSTOP: the flagged-straggler + speculation path), hard
  # kill another (leases recycle at expiry, autoscale respawns), SIGTERM
  # a third (graceful drain), then wake the frozen one so its late acks
  # hit the fence. Seeded jitter makes each seed a different storm.
  rng = random.Random(seed)
  state = {"tick": 0, "stopped": None, "resume_at": 0,
           "stalled": 0, "killed": 0, "preempted": 0, "resumed": 0}
  stall_tick = 1 + rng.randrange(2)

  def range_holder_pids():
    # worker ids are <host>-<pid>: map live range-lease holders back to
    # the local pool's processes so the freeze always lands mid-range
    pids = set()
    for r in q.range_leases():
      holder = r.get("holder") or ""
      if not r.get("expired") and "-" in holder:
        try:
          pids.add(int(holder.rsplit("-", 1)[1]))
        except ValueError:
          pass
    return pids

  def chaos_sleep(dt):
    state["tick"] += 1
    t = state["tick"]
    actuator.reap()
    procs = [p for p in actuator.procs if p.poll() is None]
    if procs and not state["stalled"] and t >= stall_tick:
      # wait for a worker that actually HOLDS a live range: freezing a
      # leaseless worker stalls nothing (it never leases again), and the
      # whole speculation path would go unexercised
      holders = range_holder_pids()
      victims = [p for p in procs if p.pid in holders]
      if victims:
        victim = victims[rng.randrange(len(victims))]
        victim.send_signal(signal.SIGSTOP)
        state.update(stalled=1, stopped=victim, stall_t=time.time(),
                     resume_at=t + 8 + rng.randrange(3))
    elif procs and not state["killed"] and t >= stall_tick + 3:
      live = [p for p in procs if p is not state["stopped"]]
      if live:
        live[-1].send_signal(signal.SIGKILL)
        state.update(killed=1, kill_t=time.time())
    elif procs and not state["preempted"] and t >= stall_tick + 6:
      live = [p for p in procs if p is not state["stopped"]]
      if live:
        live[0].send_signal(signal.SIGTERM)
        state.update(preempted=1, preempt_t=time.time())
    if state["stopped"] is not None and t >= state["resume_at"]:
      # the zombie wakes mid-campaign: everything it still thinks it
      # holds was speculated away or recycled — its acks must fence
      state["stopped"].send_signal(signal.SIGCONT)
      state["stopped"] = None
      state["resumed"] = 1
    time.sleep(dt)

  summary = runner.run(sleep_fn=chaos_sleep)
  if state["stopped"] is not None:   # never left frozen on a fast drain
    state["stopped"].send_signal(signal.SIGCONT)

  assert state["stalled"] and state["killed"], (
    f"storm never landed its faults (ticks={state['tick']}): {state}"
  )
  assert not summary["timed_out"], f"campaign timed out: {summary}"
  assert q.is_empty() and q.enqueued == 0, "hostile queue not drained"
  assert q.dlq_count == 0, f"DLQ leakage: {q.dlq_ls()}"
  # completions EXACT: double-issued twins, steals, recycles, and the
  # waking zombie's late acks must never double-count a task
  assert q.completed == n_tasks, (
    f"completions drifted: tally={q.completed} tasks={n_tasks}"
  )

  hostile = layer_bytes(os.path.join(workdir, "layer"))
  missing = sorted(set(clean) - set(hostile))
  extra = sorted(set(hostile) - set(clean))
  assert not missing and not extra, (
    f"key sets differ: missing={missing[:5]} extra={extra[:5]}"
  )
  diff = [k for k in clean if clean[k] != hostile[k]]
  assert not diff, f"{len(diff)} objects differ byte-wise: {diff[:5]}"

  # the speculation ledger must reconcile FROM THE JOURNAL ALONE —
  # issued counts on the driver, won/fenced on whichever worker's ack
  # created the done marker; fleet.status merges them
  records = fleet.load_effective(jpath)
  counters = fleet.status(records)["counters"]
  spec_issued = counters.get("speculation.issued", 0)
  spec_won = counters.get("speculation.won", 0)
  spec_fenced = counters.get("speculation.fenced", 0)
  assert spec_issued > 0, (
    f"storm never speculated — the stall was not flagged in time "
    f"(counters={counters}, history={runner.history[-5:]})"
  )
  assert spec_won + spec_fenced == spec_issued, (
    f"speculation ledger broken: issued={spec_issued} won={spec_won} "
    f"fenced={spec_fenced}"
  )

  # forecast fidelity (ISSUE 17 satellite): mine THIS hostile journal —
  # the task-duration model, the OBSERVED fleet trajectory (each
  # worker's arrival offset, replacements included), and the storm's
  # fault wall-times — then replay the campaign in the simulator with
  # speculation + stealing and demand the forecast lands within ±20% of
  # the live hostile makespan. Holding the fleet history and fault
  # schedule fixed makes this a test of the sim's execution + lease +
  # survival model, not of how well it can re-guess autoscaler latency.
  task_spans = [
    r for r in records
    if r.get("kind") == "span" and r.get("name") == "task"
  ]
  first_task_ts = min(r["ts"] for r in task_spans)
  # last FIRST-resolution, not last span end: the waking zombie's
  # interrupted spans carry the whole freeze in their dur and its acks
  # are fenced — only winners append to the completions tally, so the
  # tally file's mtime is the instant the campaign actually finished
  last_completion = os.path.getmtime(os.path.join(q.path, "completions"))
  live_makespan = last_completion - first_task_ts
  # the observed fleet trajectory: each distinct worker id's first task
  # span, offset from campaign start — replacements the live autoscaler
  # spawned mid-storm appear as later arrivals, so the sim replays the
  # real capacity trough instead of re-deriving controller latency
  first_seen = {}
  for r in task_spans:
    w = r.get("worker")
    if w and (w not in first_seen or r["ts"] < first_seen[w]):
      first_seen[w] = r["ts"]
  arrivals = sorted(
    max(ts - first_task_ts, 0.0) for ts in first_seen.values()
  )
  model = replay.WorkloadModel.mine(records)
  # the frozen worker's interrupted span carries the whole SIGSTOP
  # freeze in its dur; the ChaosSpec injects that fault explicitly, so
  # fault-inflated samples would double-count the storm
  clipped = model.clip_outliers()
  cfg = sim_mod.SimConfig(
    workers=len(arrivals), seed=seed, tasks=n_tasks,
    batch_size=4, lease_sec=20.0, range_lease=1, speculate=1, steal=1,
    steal_min_held_sec=1.0, worker_arrivals=arrivals,
    # the live driver sweeps every tick with stall_sec=3 detection
    # latency — the sim's sweep interval is the analogous lag
    speculate_interval_sec=3.0,
    # fault times replayed from the storm's own wall clock, landing on
    # the earliest arrivals — the workers the live storm actually hit
    chaos=sim_mod.ChaosSpec(
      stall=1, kill=1, preempt=state["preempted"],
      kill_at=max(state.get("kill_t", 0) - first_task_ts, 0.1),
      preempt_at=max(state.get("preempt_t", 0) - first_task_ts, 0.1),
    ),
  )
  forecast = sim_mod.FleetSimulator(model, cfg).run()
  ratio = forecast["makespan_sec"] / max(live_makespan, 1e-9)
  assert 0.8 <= ratio <= 1.2, (
    f"sim forecast diverged from the live hostile run: "
    f"forecast={forecast['makespan_sec']}s live={round(live_makespan, 3)}s "
    f"(ratio {ratio:.2f}; arrivals={[round(a, 2) for a in arrivals]} "
    f"clipped={clipped} chaos={cfg.chaos})"
  )

  return {
    "tasks": n_tasks,
    "clean_executed": n_clean,
    "completions_tally": q.completed,
    "dlq": q.dlq_count,
    "objects_compared": len(clean),
    "byte_identical": True,
    "campaign": {k: summary[k] for k in
                 ("ticks", "actions", "speculated", "wall_sec")},
    "storm": {k: state[k] for k in
              ("stalled", "killed", "preempted", "resumed")},
    "speculation": {
      "issued": spec_issued, "won": spec_won, "fenced": spec_fenced,
      "duplicate_acks": counters.get("speculation.duplicate_ack", 0),
      "wasted_ms": counters.get("speculation.wasted_ms", 0),
    },
    "steal": {
      "claims": counters.get("steal.claims", 0),
      "granted": counters.get("steal.granted", 0),
      "tasks": counters.get("steal.tasks", 0),
    },
    "zombie_fenced": counters.get("zombie.delete", 0),
    "forecast": {
      "live_makespan_sec": round(live_makespan, 3),
      "sim_makespan_sec": forecast["makespan_sec"],
      "ratio": round(ratio, 3),
      "worker_arrivals": [round(a, 2) for a in arrivals],
      "outlier_durs_clipped": clipped,
      "sim_speculation": forecast["speculation"],
      "sim_steals": forecast["steals"],
    },
  }


def main():
  ap = argparse.ArgumentParser(description=__doc__)
  ap.add_argument("--seed", type=int, default=0,
                  help="fault schedule seed (same seed = same storm)")
  ap.add_argument("--size", type=int, default=96,
                  help="cube edge of the synthetic volume")
  ap.add_argument("--keep", action="store_true",
                  help="keep the scratch dir for inspection")
  ap.add_argument("--scenario",
                  choices=("faults", "preemption", "stall", "corruption",
                           "hostile", "all"),
                  default="faults",
                  help="faults: ISSUE 1 storage/queue fault storm; "
                       "preemption: ISSUE 2 worker kill storm + zombie; "
                       "stall: ISSUE 6 stalled worker + backlog -> "
                       "`fleet check` must flag it; "
                       "corruption: ISSUE 16 silent at-rest damage -> "
                       "audit names every fault, heal converges "
                       "byte-identically; "
                       "hostile: ISSUE 17 closed-loop campaign runner "
                       "under a kill/stall/preempt + speculation storm "
                       "-> byte-identical, completions exact, ledger "
                       "reconciles, sim forecast within ±20%")
  ap.add_argument("--report-out", default=None,
                  help="write the full soak report JSON here (CI uploads "
                       "it as an artifact)")
  ap.add_argument("--trace-out", default=None,
                  help="write a Perfetto/Chrome trace JSON of the "
                       "preemption storm's merged journal here (CI "
                       "uploads it as a browsable artifact)")
  ap.add_argument("--health-out", default=None,
                  help="write the stall scenario's `fleet check` health "
                       "report JSON here (CI uploads it as an artifact)")
  ap.add_argument("--pipeline", action="store_true",
                  help="run the soak with the staged execution pipeline "
                       "enabled (ISSUE 3): the CLEAN reference run stays "
                       "strict-serial while every fault/storm run executes "
                       "through the pipeline's threaded encode/upload and "
                       "prefetch stages — byte identity must still hold")
  args = ap.parse_args()

  os.environ.setdefault("JAX_PLATFORMS", "cpu")
  if args.pipeline:
    # the clean run pins the reference bytes serially; run_pipeline's
    # FileQueue.poll drains pick the pipeline up from the env (tier-A
    # execute_with_sink), threads forced so 1-core CI still exercises
    # real concurrency
    os.environ["IGNEOUS_PIPELINE"] = "1"
    os.environ["IGNEOUS_PIPELINE_THREADS"] = "1"
  scratch = tempfile.mkdtemp(prefix="chaos-soak-")
  # full metric reset (counters AND timers/gauges/histograms): the soak
  # report must only reflect this storm — reset_counters() alone no
  # longer clears the float families (ISSUE 5 split)
  telemetry.reset_all()
  t0 = time.monotonic()
  try:
    rng = np.random.default_rng(args.seed)
    img = rng.integers(0, 255, (args.size, args.size, args.size // 2))
    img = img.astype(np.uint8)

    report = {"seed": args.seed, "scenario": args.scenario}
    if args.scenario in ("faults", "all"):
      report["faults"] = run_faults_scenario(scratch, img, args.seed)
    if args.scenario in ("preemption", "all"):
      report["preemption"] = run_preemption_storm(
        scratch, img, args.seed, trace_out=args.trace_out
      )
    if args.scenario in ("stall", "all"):
      report["stall"] = run_stall_health_scenario(
        scratch, args.seed, health_out=args.health_out
      )
    if args.scenario in ("corruption", "all"):
      report["corruption"] = run_corruption_scenario(scratch, img, args.seed)
    if args.scenario in ("hostile", "all"):
      report["hostile"] = run_hostile_scenario(scratch, args.seed)
    report["counters"] = telemetry.counters_snapshot()
    report["wall_s"] = round(time.monotonic() - t0, 2)
    if args.report_out:
      with open(args.report_out, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report, indent=2))
  finally:
    if args.keep:
      print(f"scratch kept at {scratch}", file=sys.stderr)
    else:
      shutil.rmtree(scratch, ignore_errors=True)


if __name__ == "__main__":
  main()
