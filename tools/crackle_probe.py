"""Crackle (.ckl) reverse-engineering probe — round-4 state (ROADMAP).

Run against the reference checkout's fixture:

    python tools/crackle_probe.py /root/reference/test/connectomics.npy.ckl.gz

Everything in `parse_*` below is VALIDATED byte-exactly against that
fixture (every slice and section accounted for, all 512 slices):

  container := header | crack_index | labels | crack_streams
  header (24B) := 'crkl' | u8 version(0) | u16 format(0x008a:
      data_width=4, stored_width=4, label_format=FLAT, flag bit7) |
      u32 sx,sy,sz | u8 grid_log2(31 = whole-slice grid) |
      u32 num_label_bytes
  crack_index := sz * u32 per-slice crack byte lengths
  labels(FLAT) := u64 num_uniq | u32 uniq (sorted) |
      sz * u32 components-per-slice | u16 keys (uniq index per component)
  crack stream (per slice) := u32 L | u16 seed-table (L bytes) | moves
  seed-table := records (x, dy, k, extra_x*(k-1)) ascending rows (dy
      sums to ~image height; k seeds on the row — the k-1 extras are
      ABSOLUTE x values, not deltas: raw extras never exceed the grid
      width while delta-accumulation overruns it in 280/512 slices) +
      ONE trailing u16 in every slice (suspected y=0 seed x; unproven).
      Record count anti-correlates with slice component count => seeds
      are per crack-graph component (~1 big network + islands).
  moves := 2-bit symbols, LSB-first within each byte. Relative turn code:
      0 = straight (37%), 1/3 = the two turns (staircase alternation
      dominates their bigrams), 2 = special (8.5%), runs of exactly 1-2.

What is PROVEN about the semantics (see decode_best for the closest VM):
  * the walk is CONTINUOUS through '2' symbols (inter-'2' manhattan
    distances match the move counts exactly) => '2' marks a junction in
    passing without moving;
  * '2' totals per slice ~= 2x the slice's component count — the return
    budget of a trivalent junction graph (singles=deg-3, doubles=deg-4);
  * walks legitimately close small loops through visited vertices
    (1-pixel detours observed) and run into the border wanting more —
    so the dead-end/resume trigger is an impossible (off-grid) move.

What is NOT yet pinned: the resume-target rule. Mark-stack LIFO/FIFO,
collision anchors, and derived-undrawn-edge resumes all decode the full
stream with <=3 dangling interior endpoints but land at ~2000-2500
components where the labels section says 1225 — right texture, wrong
excursion placement. Round-5 plan (ROADMAP): write the ENCODER for a
synthetic trivalent tessellation and fit the policy by matching stream
statistics, then transplant the matched rule here.
"""

from __future__ import annotations

import gzip
import struct
import sys

import numpy as np

DXY = [(0, -1), (1, 0), (0, 1), (-1, 0)]  # up right down left (clockwise)


def parse_container(blob: bytes) -> dict:
  if blob[:2] == b"\x1f\x8b":
    blob = gzip.decompress(blob)
  assert blob[:4] == b"crkl", "not a crackle stream"
  version = blob[4]
  fmt = struct.unpack("<H", blob[5:7])[0]
  sx, sy, sz = struct.unpack("<III", blob[7:19])
  grid_log2 = blob[19]
  num_label_bytes = struct.unpack("<I", blob[20:24])[0]
  idx = np.frombuffer(blob, dtype="<u4", count=sz, offset=24)
  label_off = 24 + 4 * sz
  nuniq = struct.unpack("<Q", blob[label_off:label_off + 8])[0]
  uniq = np.frombuffer(blob, dtype="<u4", count=nuniq, offset=label_off + 8)
  cc_off = label_off + 8 + 4 * nuniq
  cc_per_slice = np.frombuffer(blob, dtype="<u4", count=sz, offset=cc_off)
  keys = np.frombuffer(
    blob, dtype="<u2", count=int(cc_per_slice.sum()), offset=cc_off + 4 * sz
  )
  crack_off = label_off + num_label_bytes
  offs = crack_off + np.concatenate(
    [[0], np.cumsum(idx[:-1])]
  ).astype(np.int64)
  assert crack_off + int(idx.sum()) == len(blob), "size accounting failed"
  return {
    "version": version, "format": fmt, "shape": (sx, sy, sz),
    "grid_log2": grid_log2, "uniq": uniq, "cc_per_slice": cc_per_slice,
    "keys": keys, "crack_index": idx, "slice_offsets": offs, "blob": blob,
  }


def parse_slice(c: dict, z: int):
  """-> (seeds [(x, y)...] ascending rows, trailing u16s, 2-bit symbols).

  The final byte's unused bit pairs decode as up-to-3 phantom '0'
  symbols — the stream carries no explicit symbol count, so consumers
  doing statistics should ignore the last byte's worth of symbols."""
  blob = c["blob"]
  s = blob[c["slice_offsets"][z]:c["slice_offsets"][z] + c["crack_index"][z]]
  L = struct.unpack("<I", s[:4])[0]
  t = np.frombuffer(s[4:4 + L], dtype="<u2")
  mv = np.frombuffer(s[4 + L:], dtype=np.uint8)
  syms = np.stack(
    [mv & 3, (mv >> 2) & 3, (mv >> 4) & 3, (mv >> 6) & 3], axis=1
  ).ravel()
  i = 0
  seeds = []
  y = 0
  trailing = []
  while i < len(t):
    if i + 3 > len(t):
      trailing = [int(v) for v in t[i:]]
      break
    x, dy, k = int(t[i]), int(t[i + 1]), int(t[i + 2])
    i += 3
    y += dy
    xs = [x]
    for _ in range(k - 1):
      xs.append(int(t[i]))  # absolute x, not a delta (see docstring)
      i += 1
    seeds.extend((xx, y) for xx in xs)
  return seeds, trailing, syms


def decode_best(seeds, syms, sx=512, sy=512, chir=True, d0=0):
  """Closest VM so far (NOT correct — see module docstring): continuous
  relative walk, '2' pushes a junction mark, an off-grid move pops the
  most recent mark and resumes along its first undrawn edge."""
  x, y = seeds[0]
  d = d0
  ci = 1
  marks = []
  vcr = np.zeros((sx + 1, sy), bool)
  hcr = np.zeros((sx, sy + 1), bool)

  def draw(x, y, d, nx, ny):
    if d == 0: vcr[x, ny] = True
    elif d == 2: vcr[x, y] = True
    elif d == 1: hcr[x, y] = True
    else: hcr[nx, y] = True

  def undrawn(x, y):
    out = []
    if y - 1 >= 0 and not vcr[x, y - 1]: out.append(0)
    if x + 1 <= sx and x <= sx - 1 and not hcr[x, y]: out.append(1)
    if y + 1 <= sy and y <= sy - 1 and not vcr[x, y]: out.append(2)
    if x - 1 >= 0 and not hcr[x - 1, y]: out.append(3)
    return out

  n = len(syms)
  si = 0
  while si < n:
    s = int(syms[si]); si += 1
    if chir and s in (1, 3): s = 4 - s
    if s == 2:
      marks.append((x, y))
      continue
    d2 = (d + s) % 4
    nx, ny = x + DXY[d2][0], y + DXY[d2][1]
    if not (0 <= nx <= sx and 0 <= ny <= sy):
      if marks:
        x, y = marks.pop()
        free = undrawn(x, y)
        if free:
          d = free[0]
          nx, ny = x + DXY[d][0], y + DXY[d][1]
          draw(x, y, d, nx, ny)
          x, y = nx, ny
        continue
      if ci < len(seeds):
        x, y = seeds[ci]; ci += 1; d = d0
        continue
      break
    d = d2
    draw(x, y, d, nx, ny)
    x, y = nx, ny
  return vcr, hcr


def components(vcr, hcr, sx=512, sy=512) -> int:
  parent = np.arange(sx * sy, dtype=np.int64)

  def find(a):
    while parent[a] != a:
      parent[a] = parent[parent[a]]
      a = parent[a]
    return a

  xs, ys = np.where(~vcr[1:sx, :])
  for x, y in zip(xs, ys):
    ra, rb = find(x * sy + y), find((x + 1) * sy + y)
    if ra != rb: parent[rb] = ra
  xs, ys = np.where(~hcr[:, 1:sy])
  for x, y in zip(xs, ys):
    ra, rb = find(x * sy + y), find(x * sy + y + 1)
    if ra != rb: parent[rb] = ra
  return len({find(i) for i in range(sx * sy)})


if __name__ == "__main__":
  path = sys.argv[1] if len(sys.argv) > 1 else (
    "/root/reference/test/connectomics.npy.ckl.gz"
  )
  with open(path, "rb") as f:
    c = parse_container(f.read())
  sx, sy, sz = c["shape"]
  print(f"crackle v{c['version']} format=0x{c['format']:04x} "
        f"{sx}x{sy}x{sz} labels={len(c['uniq'])} "
        f"components={int(c['cc_per_slice'].sum())}")
  for z in (0, sz // 2, sz - 1):
    seeds, trailing, syms = parse_slice(c, z)
    n2 = int((syms == 2).sum())
    vcr, hcr = decode_best(seeds, syms, sx, sy)
    cc = components(vcr, hcr, sx, sy)
    print(f"  z={z}: seeds={len(seeds)}+{trailing} syms={len(syms)} "
          f"twos={n2} decode_best cc={cc} vs truth {c['cc_per_slice'][z]}")
