#!/usr/bin/env python
"""Integrity audit smoke (ISSUE 16 CI step).

Runs a real downsample campaign through `igneous execute` on a virtual
8-device CPU mesh (so manifests are written by the same worker path
production uses), then damages the layer at rest with three distinct
fault shapes — a torn write (truncation), a flipped bit, and a deleted
object — and asserts the audit plane end to end:

  * `igneous audit` exits 2 and NAMES each of the three damaged chunks
    on stdout (CORRUPT <kind> mip=<m> <key> lines);
  * `igneous audit --heal` re-runs the producing tasks for exactly the
    damaged cells through an fq:// range-lease queue and exits 0;
  * a follow-up plain audit confirms convergence (exit 0);
  * the machine-readable completeness reports land where CI can upload
    them as artifacts (--report-out).

Usage: python tools/audit_smoke.py [--size 128] [--report-out DIR]
"""

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def worker_env():
  env = dict(os.environ)
  env.update({
    "JAX_PLATFORMS": "cpu",
    "PALLAS_AXON_POOL_IPS": "",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    "IGNEOUS_POOL_HOST": "0",
    "IGNEOUS_PIPELINE": "1",
    "IGNEOUS_PIPELINE_THREADS": "1",
  })
  env.pop("AXON_POOL_SVC_OVERRIDE", None)
  env.pop("AXON_LOOPBACK_RELAY", None)
  return env


def run(argv, timeout=600):
  proc = subprocess.run(
    [sys.executable, "-m", "igneous_tpu"] + argv,
    env=worker_env(), cwd=REPO, capture_output=True, text=True,
    timeout=timeout,
  )
  sys.stdout.write(proc.stdout)
  sys.stderr.write(proc.stderr)
  return proc


def produced_chunks(layer_dir, mip0_dir):
  """Chunk files of every produced (non-source) mip, sorted for a
  deterministic corruption target set."""
  out = []
  for entry in sorted(os.listdir(layer_dir)):
    full = os.path.join(layer_dir, entry)
    if not os.path.isdir(full) or entry == mip0_dir:
      continue
    if entry in ("integrity",):
      continue
    for name in sorted(os.listdir(full)):
      if "-" in name:  # bbox-named chunk, not a sidecar
        out.append(os.path.join(full, name))
  return out


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument("--size", type=int, default=256)
  ap.add_argument("--report-out", default=None,
                  help="Copy audit completeness reports here (CI upload).")
  args = ap.parse_args()

  tmp = tempfile.mkdtemp(prefix="igneous-audit-smoke-")
  path = f"file://{tmp}/img"
  layer_dir = os.path.join(tmp, "img")
  qspec = f"fq://{tmp}/q"
  auditq = f"fq://{tmp}/auditq"

  from igneous_tpu import task_creation as tc
  from igneous_tpu.queues import FileQueue
  from igneous_tpu.volume import Volume

  rng = np.random.default_rng(11)
  n = args.size
  data = rng.integers(0, 255, (n, n, 64)).astype(np.uint8)
  vol = Volume.from_numpy(data, path, chunk_size=(32, 32, 32),
                          compress="gzip", layer_type="image")
  mip0_dir = vol.meta.key(0)
  # memory_target sized so the default 256x256x64 volume plans the full
  # 2-mip pyramid ([128,128,64] task shape) across a 4-task grid
  tasks = list(tc.create_downsampling_tasks(
    path, mip=0, num_mips=2, memory_target=4 * 1024 * 1024,
    compress="gzip",
  ))
  assert len(tasks) >= 4, f"want a fan-out of tasks, got {len(tasks)}"
  FileQueue(qspec).insert(tasks)

  proc = run(["execute", qspec, "--batch", "4", "--exit-on-empty",
              "--min-sec", "10", "-q", "--lease-sec", "60"])
  assert proc.returncode == 0, f"campaign worker failed rc={proc.returncode}"

  # a clean campaign must audit clean before we break anything
  proc = run(["audit", path, "--queue", auditq])
  assert proc.returncode == 0, (
    f"clean-campaign audit exited {proc.returncode}: {proc.stdout}"
  )

  chunks = produced_chunks(layer_dir, mip0_dir)
  assert len(chunks) >= 3, f"need >=3 produced chunks, got {len(chunks)}"
  targets = [chunks[0], chunks[len(chunks) // 2], chunks[-1]]
  assert len(set(targets)) == 3

  def logical_key(full):
    rel = os.path.relpath(full, layer_dir)
    for ext in (".gz", ".zstd", ".br"):
      if rel.endswith(ext):
        return rel[: -len(ext)]
    return rel

  torn, flipped, deleted = targets
  with open(torn, "r+b") as f:
    f.truncate(max(1, os.path.getsize(torn) // 2))
  raw = open(flipped, "rb").read()
  i = len(raw) // 2
  with open(flipped, "wb") as f:
    f.write(raw[:i] + bytes([raw[i] ^ 0x10]) + raw[i + 1:])
  os.remove(deleted)
  injected = {logical_key(t) for t in targets}
  print(f"injected 3 faults: {sorted(injected)}")

  report1 = os.path.join(tmp, "audit-findings.json")
  proc = run(["audit", path, "--queue", auditq, "--out", report1])
  assert proc.returncode == 2, (
    f"audit over damaged layer exited {proc.returncode}, want 2"
  )
  named = {
    line.split()[-1]
    for line in proc.stdout.splitlines() if line.startswith("CORRUPT ")
  }
  assert named == injected, (
    f"audit must name exactly the injected faults: "
    f"missed={sorted(injected - named)} extra={sorted(named - injected)}"
  )
  rep = json.load(open(report1))
  assert not rep["complete"] and len(rep["findings"]) == 3, rep

  report2 = os.path.join(tmp, "audit-healed.json")
  proc = run(["audit", path, "--queue", auditq, "--heal", "--out", report2])
  assert proc.returncode == 0, (
    f"audit --heal exited {proc.returncode}: {proc.stdout}"
  )
  assert "complete and intact" in proc.stdout
  rep = json.load(open(report2))
  assert rep["complete"] and rep["repair_tasks"] >= 1, rep

  # convergence: a fresh audit of the healed layer is clean
  proc = run(["audit", path, "--queue", auditq])
  assert proc.returncode == 0, f"post-heal audit exited {proc.returncode}"

  if args.report_out:
    os.makedirs(args.report_out, exist_ok=True)
    for rpt in (report1, report2):
      shutil.copyfile(
        rpt, os.path.join(args.report_out, os.path.basename(rpt))
      )
    print(f"copied reports to {args.report_out}")

  shutil.rmtree(tmp, ignore_errors=True)
  print("AUDIT_SMOKE_OK")


if __name__ == "__main__":
  main()
