"""TPU-revival watcher (VERDICT r3 item 4): never lose a healthy window.

Rounds 1-3 never produced a TPU-platform bench artifact: the axon relay
stalled for entire rounds, and round 2's one ~30-minute healthy window was
lost to a full-length bench run colliding with a second jax process. This
watcher makes the revival protocol unlosable:

  1. probe the tunnel in a disposable subprocess on an interval;
  2. the moment a probe succeeds, run ``BENCH_QUICK=1`` FIRST (minutes)
     and write its artifact to ``BENCH_TPU_QUICK.json`` immediately;
  3. then attempt, each as a separate supervised child so a mid-run stall
     keeps every earlier result: the full bench (``BENCH_TPU_FULL.json``),
     the pool A/B + CCL scan-vs-relax + EDT-at-512^3 kernel decisions
     (``BENCH_TPU_KERNELS.json``).

Run:  python tpu_watch.py [--interval 600] [--once]
Each completed stage appends a JSON line to ``TPU_WATCH_LOG.jsonl``.
"""

import argparse
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.abspath(__file__))
LOG = os.path.join(_REPO, "TPU_WATCH_LOG.jsonl")


def log_event(**kw):
  kw["t"] = time.strftime("%Y-%m-%dT%H:%M:%S")
  with open(LOG, "a") as f:
    f.write(json.dumps(kw) + "\n")
  print(json.dumps(kw), flush=True)


def probe(timeout_s: float = 45) -> bool:
  try:
    proc = subprocess.run(
      [sys.executable, "-c",
       "import jax; print(jax.devices()[0].platform)"],
      capture_output=True, text=True, timeout=timeout_s, cwd=_REPO,
    )
    return proc.returncode == 0 and proc.stdout.strip() in ("axon", "tpu")
  except subprocess.TimeoutExpired:
    return False


def run_stage(name: str, cmd, env_extra, timeout_s: float, out_path=None):
  """Supervised child; write its last JSON line to out_path. Returns ok."""
  env = dict(os.environ)
  env.update(env_extra)
  t0 = time.time()
  try:
    proc = subprocess.run(
      cmd, env=env, cwd=_REPO, capture_output=True, text=True,
      timeout=timeout_s,
    )
  except subprocess.TimeoutExpired:
    log_event(stage=name, ok=False, error=f"timeout {timeout_s}s")
    return False
  took = round(time.time() - t0, 1)
  if proc.returncode != 0:
    log_event(stage=name, ok=False, rc=proc.returncode,
              stderr=proc.stderr[-500:], took_s=took)
    return False
  result = None
  for line in reversed(proc.stdout.strip().splitlines()):
    try:
      result = json.loads(line)
      break
    except (json.JSONDecodeError, ValueError):
      continue
  if out_path:
    if result is None:
      # rc 0 but no JSON = no artifact: report failure, or the watcher
      # would re-run this stage on every window yet never complete
      log_event(stage=name, ok=False, took_s=took,
                error="no JSON line in child stdout")
      return False
    with open(out_path, "w") as f:
      json.dump(result, f)
  platform = (result or {}).get("detail", {}).get("platform", "?")
  log_event(stage=name, ok=True, took_s=took, platform=platform,
            value=(result or {}).get("value"))
  return True


KERNEL_AB_SNIPPET = r"""
import json, time
import numpy as np
import bench

out = {"metric": "tpu_kernel_ab", "unit": "mixed", "value": 1, "detail": {}}
d = out["detail"]
d["pool_ab"] = bench.bench_pool_ab()
d["ccl_scan_voxps"] = round(bench.bench_ccl_kernel("scan"), 1)
d["ccl_relax_voxps"] = round(bench.bench_ccl_kernel("relax"), 1)
d["edt_128_voxps"] = round(bench.bench_edt_kernel(), 1)
# EDT at 512^3 single volume (BASELINE config 5 core at production size)
from igneous_tpu.ops.edt import edt
lab = (np.random.default_rng(0).integers(0, 3, (512, 512, 512)) * 9).astype(np.uint32)
edt(lab[:64, :64, :64], (4, 4, 40))  # compile
t0 = time.perf_counter()
edt(lab, (4, 4, 40))
d["edt_512_voxps"] = round(lab.size / (time.perf_counter() - t0), 1)
import jax
d["platform"] = jax.default_backend()
print(json.dumps(out))
"""


BATCH_E2E_SNIPPET = r"""
import json, os, tempfile, time
import numpy as np

os.environ["IGNEOUS_POOL_HOST"] = "0"  # this measures the chip, not the host
import jax
from igneous_tpu import task_creation as tc
from igneous_tpu.parallel import make_mesh
from igneous_tpu.parallel.lease_batcher import poll_batched
from igneous_tpu.queues import FileQueue
from igneous_tpu.volume import Volume

rng = np.random.default_rng(0)
data = rng.integers(0, 255, (1024, 512, 64)).astype(np.uint8)
td = tempfile.mkdtemp()
stats = None
for rep in ("warmup", "timed"):  # rep 1 pays the XLA compile
  path = f"file://{td}/img_{rep}"
  Volume.from_numpy(data, path, chunk_size=(64, 64, 64))
  tasks = tc.create_downsampling_tasks(
    path, mip=0, num_mips=2, compress=None, memory_target=int(4e6))
  q = FileQueue(f"fq://{td}/q_{rep}")
  q.insert(tasks)
  t0 = time.perf_counter()
  executed, stats = poll_batched(
    q, batch_size=8, lease_seconds=600,
    stop_fn=lambda executed, empty: empty, mesh=make_mesh())
  dt = time.perf_counter() - t0
voxps = data.size / dt
print(json.dumps({
  "metric": "tpu_batch_e2e_voxps", "value": round(voxps, 1), "unit": "vox/s",
  "detail": {"executed": executed, "stats": {
    k: (dict(v) if hasattr(v, "items") else v) for k, v in stats.items()},
    "wall_s": round(dt, 2), "platform": jax.default_backend()},
}))
"""


# (name, cmd, env_extra, timeout_s, artifact) — quick bench FIRST so an
# artifact lands within minutes of any healthy window
def _stages():
  return [
    ("bench-quick", [sys.executable, "bench.py", "--child", "tpu"],
     {"BENCH_QUICK": "1"}, 1200, "BENCH_TPU_QUICK.json"),
    ("bench-full", [sys.executable, "bench.py", "--child", "tpu"],
     {}, 3600, "BENCH_TPU_FULL.json"),
    ("bench-kernels", [sys.executable, "-c", KERNEL_AB_SNIPPET],
     {}, 3600, "BENCH_TPU_KERNELS.json"),
    # north-star path on hardware: queue-leased --batch worker on-chip
    ("bench-batch", [sys.executable, "-c", BATCH_E2E_SNIPPET],
     {}, 3600, "BENCH_TPU_BATCH.json"),
  ]


def missing_stages():
  return [
    s for s in _stages() if not os.path.exists(os.path.join(_REPO, s[4]))
  ]


def on_revival():
  """Run every stage whose artifact is still missing. A quick-bench
  failure aborts the pass (the window is dead); later-stage failures
  keep earlier artifacts and stay eligible for the NEXT healthy window
  (ADVICE r4: quick-only is a partial revival, not watch-complete)."""
  log_event(stage="revival-detected", ok=True,
            missing=[s[0] for s in missing_stages()])
  for i, (name, cmd, env_extra, timeout_s, artifact) in enumerate(
    missing_stages()
  ):
    if i > 0 and not probe():
      # the window died mid-pass: abort rather than burning hours of
      # serial subprocess timeouts against a dead tunnel (a 45s probe
      # between stages keeps the watcher responsive to the NEXT window)
      log_event(stage="mid-pass-probe", ok=False, before=name)
      return False
    ok = run_stage(
      name, cmd, env_extra, timeout_s,
      out_path=os.path.join(_REPO, artifact),
    )
    if not ok and name == "bench-quick":
      return False  # window died before the cheapest stage: re-probe
  return not missing_stages()


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument("--interval", type=float, default=600)
  ap.add_argument("--once", action="store_true",
                  help="probe once and exit (0 = all artifacts captured)")
  args = ap.parse_args()
  while True:
    if not missing_stages():
      log_event(stage="watch-complete", ok=True)
      return 0
    if probe():
      if on_revival():
        log_event(stage="watch-complete", ok=True)
        return 0
      if args.once:
        # probe succeeded but some artifact is still missing: partial
        # revival — exit nonzero so supervisors keep watching
        return 2
      # keep watching: later healthy windows recover the missing stages
    elif args.once:
      log_event(stage="probe", ok=False)
      return 1
    time.sleep(args.interval)


if __name__ == "__main__":
  sys.exit(main())
