"""TPU-revival watcher (VERDICT r3 item 4): never lose a healthy window.

Rounds 1-3 never produced a TPU-platform bench artifact: the axon relay
stalled for entire rounds, and round 2's one ~30-minute healthy window was
lost to a full-length bench run colliding with a second jax process. This
watcher makes the revival protocol unlosable:

  1. probe the tunnel in a disposable subprocess on an interval;
  2. the moment a probe succeeds, run ``BENCH_QUICK=1`` FIRST (minutes)
     and write its artifact to ``BENCH_TPU_QUICK.json`` immediately;
  3. then attempt, each as a separate supervised child so a mid-run stall
     keeps every earlier result: the full bench (``BENCH_TPU_FULL.json``),
     the pool A/B + CCL scan-vs-relax + EDT-at-512^3 kernel decisions
     (``BENCH_TPU_KERNELS.json``).

Run:  python tpu_watch.py [--interval 600] [--once]
Each completed stage appends a JSON line to ``TPU_WATCH_LOG.jsonl``.
"""

import argparse
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.abspath(__file__))
LOG = os.path.join(_REPO, "TPU_WATCH_LOG.jsonl")


def log_event(**kw):
  kw["t"] = time.strftime("%Y-%m-%dT%H:%M:%S")
  with open(LOG, "a") as f:
    f.write(json.dumps(kw) + "\n")
  print(json.dumps(kw), flush=True)


def probe(timeout_s: float = 45) -> bool:
  try:
    proc = subprocess.run(
      [sys.executable, "-c",
       "import jax; print(jax.devices()[0].platform)"],
      capture_output=True, text=True, timeout=timeout_s, cwd=_REPO,
    )
    return proc.returncode == 0 and proc.stdout.strip() in ("axon", "tpu")
  except subprocess.TimeoutExpired:
    return False


def run_stage(name: str, cmd, env_extra, timeout_s: float, out_path=None):
  """Supervised child; write its last JSON line to out_path. Returns ok."""
  env = dict(os.environ)
  env.update(env_extra)
  t0 = time.time()
  try:
    proc = subprocess.run(
      cmd, env=env, cwd=_REPO, capture_output=True, text=True,
      timeout=timeout_s,
    )
  except subprocess.TimeoutExpired:
    log_event(stage=name, ok=False, error=f"timeout {timeout_s}s")
    return False
  took = round(time.time() - t0, 1)
  if proc.returncode != 0:
    log_event(stage=name, ok=False, rc=proc.returncode,
              stderr=proc.stderr[-500:], took_s=took)
    return False
  result = None
  for line in reversed(proc.stdout.strip().splitlines()):
    try:
      result = json.loads(line)
      break
    except (json.JSONDecodeError, ValueError):
      continue
  if out_path and result is not None:
    with open(out_path, "w") as f:
      json.dump(result, f)
  platform = (result or {}).get("detail", {}).get("platform", "?")
  log_event(stage=name, ok=True, took_s=took, platform=platform,
            value=(result or {}).get("value"))
  return True


KERNEL_AB_SNIPPET = r"""
import json, time
import numpy as np
import bench

out = {"metric": "tpu_kernel_ab", "unit": "mixed", "value": 1, "detail": {}}
d = out["detail"]
d["pool_ab"] = bench.bench_pool_ab()
d["ccl_scan_voxps"] = round(bench.bench_ccl_kernel("scan"), 1)
d["ccl_relax_voxps"] = round(bench.bench_ccl_kernel("relax"), 1)
d["edt_128_voxps"] = round(bench.bench_edt_kernel(), 1)
# EDT at 512^3 single volume (BASELINE config 5 core at production size)
from igneous_tpu.ops.edt import edt
lab = (np.random.default_rng(0).integers(0, 3, (512, 512, 512)) * 9).astype(np.uint32)
edt(lab[:64, :64, :64], (4, 4, 40))  # compile
t0 = time.perf_counter()
edt(lab, (4, 4, 40))
d["edt_512_voxps"] = round(lab.size / (time.perf_counter() - t0), 1)
import jax
d["platform"] = jax.default_backend()
print(json.dumps(out))
"""


def on_revival():
  log_event(stage="revival-detected", ok=True)
  # 1. quick bench FIRST: minutes, artifact lands immediately
  ok_quick = run_stage(
    "bench-quick",
    [sys.executable, "bench.py", "--child", "tpu"],
    {"BENCH_QUICK": "1"},
    timeout_s=1200,
    out_path=os.path.join(_REPO, "BENCH_TPU_QUICK.json"),
  )
  if not ok_quick:
    return False
  # 2. full bench
  run_stage(
    "bench-full",
    [sys.executable, "bench.py", "--child", "tpu"],
    {},
    timeout_s=3600,
    out_path=os.path.join(_REPO, "BENCH_TPU_FULL.json"),
  )
  # 3. parked kernel decisions (pool A/B, CCL scan-vs-relax, EDT 512^3)
  run_stage(
    "bench-kernels",
    [sys.executable, "-c", KERNEL_AB_SNIPPET],
    {},
    timeout_s=3600,
    out_path=os.path.join(_REPO, "BENCH_TPU_KERNELS.json"),
  )
  return True


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument("--interval", type=float, default=600)
  ap.add_argument("--once", action="store_true",
                  help="probe once and exit (0 = revival handled)")
  args = ap.parse_args()
  while True:
    if probe():
      handled = on_revival()
      if handled:
        log_event(stage="watch-complete", ok=True)
        return 0
      if args.once:
        # probe succeeded but the quick bench did not land: the window
        # is NOT handled — exit nonzero so supervisors keep watching
        return 2
      # keep watching: the window may have been too short; try again
    elif args.once:
      log_event(stage="probe", ok=False)
      return 1
    time.sleep(args.interval)


if __name__ == "__main__":
  sys.exit(main())
