# Worker image for igneous-tpu queue execution.
#
# Reference analogue: /root/reference/Dockerfile (python slim worker whose
# CMD polls the queue). TPU-first difference: the production image is meant
# for GKE TPU node pools, so jax[tpu] is installed and one pod drives all
# the host's chips via the batched executor (deployment.yaml).

FROM python:3.11-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
      g++ \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY pyproject.toml README.md ./
COPY igneous_tpu ./igneous_tpu

# jax[tpu] resolves libtpu on TPU VMs; harmless (cpu jax) elsewhere
RUN pip install --no-cache-dir "jax[tpu]" \
      -f https://storage.googleapis.com/jax-releases/libtpu_releases.html \
    && pip install --no-cache-dir .

ENV QUEUE_URL="fq:///queue" \
    LEASE_SECONDS="600" \
    WORKER_BATCH="1"

# the same worker loop the reference container runs (its Dockerfile CMD is
# `igneous execute -q --lease-sec $LEASE_SECONDS $SQS_URL`). exec keeps the
# worker as PID 1 so Kubernetes SIGTERM reaches it and leases release fast.
# WORKER_BATCH>1 turns on queue-leased batched execution (SURVEY §5.8):
# a TPU host leases K compatible tasks per round and runs their device
# stage as one sharded dispatch. Leave 1 on CPU-only workers.
CMD ["sh", "-c", "exec igneous-tpu execute \"$QUEUE_URL\" --lease-sec \"$LEASE_SECONDS\" --batch \"$WORKER_BATCH\" --time"]
